"""ray_tpu.rl — the training↔serving RL flywheel.

The trainer (`ray_tpu.train.loop.TrainLoop`) and the sampler
(`ray_tpu.serve.engine.InferenceEngine`) finally meet: the engine's
paged-KV continuous-batching path generates the tokens policies train
on, and trained weights hot-swap back into the live engine with no
recompile and no restart (`InferenceEngine.update_params`). Podracer's
Anakin/Sebulba split (2104.06272) maps onto the pair — the engine is
the colocated "actor" half — and MindSpeed RL (2507.19017) is the
blueprint for the in-place weight sync between them.

- `EngineSampler` / `TokenEnvRunner` (`sampler.py`): engine-backed
  rollouts returning SampleBatch trajectories with per-token logprobs
  and `params_version` staleness tags; registers the "engine"
  generation backend with `rllib.rollout.make_env_runner`.
- `FlywheelLoop` (`flywheel.py`): colocated trainer↔generator driver —
  TrainLoop steps a PPO/REINFORCE-on-sequences objective on engine
  rollouts and publishes each update into the live engine (and any
  remote `InferenceReplica`s) through TrainLoop's `publisher` hook.
"""

__all__ = ["EngineSampler", "TokenEnvRunner", "FlywheelLoop",
           "motif_reward"]

# jax loads lazily (PEP 562), same idiom as ray_tpu.serve.
_LAZY = {"EngineSampler": "ray_tpu.rl.sampler",
         "TokenEnvRunner": "ray_tpu.rl.sampler",
         "FlywheelLoop": "ray_tpu.rl.flywheel",
         "motif_reward": "ray_tpu.rl.flywheel"}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
