"""Engine-backed rollout generation for token-level RL.

`EngineSampler` submits prompts to a live `serve.engine.InferenceEngine`
and turns the streamed `TokenEvent`s (token id + behavior logprob +
params_version) into SampleBatch-compatible trajectories — so RLHF-style
learners train on tokens sampled by the same paged-KV, continuous-
batching, (optionally) speculative path that serves traffic, instead of
paying a full-sequence forward per sampled token.

`TokenEnvRunner` adapts the sampler to the `rllib.rollout` runner
contract (`sample(params) -> (SampleBatch, last_value)` +
`pop_episode_stats()`) and registers as the "engine" generation backend:
token-level envs plug into RolloutWorker via
`generation_backend="engine"` while gym envs keep the eager loop.

A token-level env is anything with:
  ``make_prompt(rng) -> sequence of token ids``  (rng: np.random.Generator)
  ``reward(prompt, completion) -> float``
  optional ``eos_id`` attribute.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.rollout import register_generation_backend
from ray_tpu.rllib.sample_batch import SampleBatch

# Extra trajectory columns (beyond the sb.* constants) the flywheel
# learner consumes. Every trajectory carries PARAMS_VERSION so learners
# can bound staleness / importance-correct against the publisher.
TOKENS = "tokens"                 # [B, T] padded prompt + completion
START = "start"                   # [B] first completion index
MASK = "mask"                     # [B, W] 1.0 on real completion tokens
PARAMS_VERSION = "params_version"  # [B, W] per-token weight version


class EngineSampler:
    """Rollout backend over a live InferenceEngine.

    `rollout(prompts)` submits every prompt up front (they continuous-
    batch into the engine's slots), drains the token streams, and packs
    one fixed-shape SampleBatch: behavior logprobs come off the
    `TokenEvent`s the engine's jitted decode/verify paths computed —
    natural (temperature-1) log pi(a|s), the quantity RL ratios need —
    and every token carries the `params_version` it was sampled under.

    `pad_to` fixes the padded sequence width [B, pad_to] (default: the
    engine's max_len) so the learner's jitted step compiles once.
    """

    def __init__(self, engine, *, max_new_tokens: int = 8,
                 temperature: float = 1.0, eos_id: int | None = None,
                 pad_to: int | None = None):
        self.engine = engine
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.pad_to = int(pad_to) if pad_to is not None else engine.max_len
        # last-rollout throughput (bench_infer's rollout_tok_s probe)
        self.last_rollout_tok_s = 0.0
        self.last_rollout_tokens = 0

    def rollout(self, prompts, reward_fn=None) -> SampleBatch:
        """prompts: list of token-id sequences -> SampleBatch with
        columns TOKENS/START/MASK/PARAMS_VERSION plus sb.ACTIONS (the
        completion tokens), sb.ACTION_LOGP (behavior logprobs),
        sb.REWARDS (reward_fn per sequence, else zeros), sb.DONES,
        sb.EPS_ID."""
        eng, W = self.engine, self.max_new_tokens
        B = len(prompts)
        if B == 0:
            raise ValueError("rollout needs at least one prompt")
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        t0 = time.perf_counter()
        rids = [eng.submit(p, max_new_tokens=W,
                           temperature=self.temperature,
                           eos_id=self.eos_id) for p in prompts]
        # Draining rid 0 pumps the shared engine, so later requests are
        # usually finished by the time their turn comes — one
        # continuously-batched device loop, not B sequential decodes.
        outs = [list(eng.tokens_for(rid)) for rid in rids]
        dt = time.perf_counter() - t0
        n_tok = sum(len(o) for o in outs)
        self.last_rollout_tokens = n_tok
        self.last_rollout_tok_s = n_tok / dt if dt > 0 else 0.0

        T = self.pad_to
        tokens = np.zeros((B, T), np.int32)
        actions = np.zeros((B, W), np.int32)
        logp = np.zeros((B, W), np.float32)
        vers = np.zeros((B, W), np.int32)
        mask = np.zeros((B, W), np.float32)
        start = np.zeros((B,), np.int32)
        rewards = np.zeros((B,), np.float32)
        for b, (p, out) in enumerate(zip(prompts, outs)):
            if p.size + len(out) > T:
                raise ValueError(
                    f"prompt {p.size} + completion {len(out)} exceeds "
                    f"pad_to {T}")
            tokens[b, :p.size] = p
            start[b] = p.size
            comp = np.asarray([int(t) for t in out], np.int32)
            tokens[b, p.size:p.size + comp.size] = comp
            actions[b, :comp.size] = comp
            logp[b, :comp.size] = [getattr(t, "logprob", 0.0)
                                   for t in out]
            vers[b, :comp.size] = [getattr(t, "params_version", 0)
                                   for t in out]
            mask[b, :comp.size] = 1.0
            if reward_fn is not None:
                rewards[b] = float(reward_fn(p, comp))
        return SampleBatch({
            TOKENS: tokens, START: start, MASK: mask,
            PARAMS_VERSION: vers,
            sb.ACTIONS: actions,
            sb.ACTION_LOGP: logp,
            sb.REWARDS: rewards,
            sb.DONES: np.ones((B,), bool),
            sb.EPS_ID: np.asarray(rids, np.int64),
        })


class TokenEnvRunner:
    """`rllib.rollout` runner contract over an EngineSampler.

    Each `sample(params)` call: (1) hot-swaps `params` into the engine
    when a NEW params object arrives (`publish=True`, the on-policy
    default — set_weights→sample stays in sync with the learner, and
    repeated samples on the same weights don't re-swap); (2) draws
    `rollout_length` prompts from the env; (3) returns the engine
    trajectory batch and a zero bootstrap value (sequence-level rewards
    have no tail to bootstrap)."""

    def __init__(self, env, module, rollout_length: int, *,
                 seed: int = 0, engine=None, engine_factory=None,
                 publish: bool = True, max_new_tokens: int = 8,
                 temperature: float = 1.0, pad_to: int | None = None):
        if engine is None:
            if engine_factory is None:
                raise ValueError(
                    "TokenEnvRunner needs engine= or engine_factory= "
                    "(an InferenceEngine to generate with)")
            engine = engine_factory()
        self.env = env
        self.module = module
        self.rollout_length = int(rollout_length)
        self.publish = publish
        self.sampler = EngineSampler(
            engine, max_new_tokens=max_new_tokens,
            temperature=temperature,
            eos_id=getattr(env, "eos_id", None), pad_to=pad_to)
        self._rng = np.random.default_rng(seed)
        self._last_params = None
        self._episode_rewards: list = []

    def sample(self, params):
        if (self.publish and params is not None
                and params is not self._last_params):
            self.sampler.engine.update_params(params)
            self._last_params = params
        prompts = [self.env.make_prompt(self._rng)
                   for _ in range(self.rollout_length)]
        batch = self.sampler.rollout(prompts, self.env.reward)
        self._episode_rewards.extend(batch[sb.REWARDS].tolist())
        return batch, np.zeros((len(prompts),), np.float32)

    def pop_episode_stats(self) -> dict:
        rs = self._episode_rewards
        stats = {
            "episode_reward_mean": (float(np.mean(rs)) if rs
                                    else float("nan")),
            "episode_len_mean": float(self.sampler.max_new_tokens),
            "episodes_this_iter": len(rs),
        }
        self._episode_rewards = []
        return stats


def _engine_backend(env, module, rollout_length, *, seed=0, **kw):
    return TokenEnvRunner(env, module, rollout_length, seed=seed, **kw)


register_generation_backend("engine", _engine_backend)
