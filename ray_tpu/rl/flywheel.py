"""FlywheelLoop — colocated trainer↔generator RL driver.

One machine-resident cycle per iteration (Anakin-style colocation,
2104.06272: both halves share the same devices, no weight shipping over
a network):

    TrainLoop step (PPO-on-sequences)  ──publisher──▶  engine.update_params
           ▲                                               │ (in-place
           │ trajectories                                  │  donated swap,
           │ (tokens + behavior logprobs                   ▼  no recompile)
           │  + params_version tags)                 InferenceEngine
           └────────────────────────  EngineSampler ◀──────┘

Generation for iteration N+1 runs AFTER iteration N's weights publish
(the batch iterator is lazy and `TrainLoop.publisher` fires between
dispatches), so rollouts are on-policy up to the engine's in-flight
sequences — whose tokens carry older `params_version` tags the learner
can mask or importance-correct with. The objective is a clipped
surrogate (PPO) on whole sampled sequences: the ratio is
exp(logp_new − behavior_logp) with behavior logprobs taken from the
engine's emitted `TokenEvent`s, the advantage is the sequence reward
minus an EMA baseline, and setting `clip=None` recovers plain
REINFORCE-with-baseline. `models.gpt.completion_logprobs` provides the
differentiable recompute of exactly the quantity the engine emitted.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rl.sampler import (EngineSampler, MASK, PARAMS_VERSION,
                                START, TOKENS)


def motif_reward(motif: int):
    """Reward = fraction of completion tokens equal to `motif` — the
    smallest objective that proves the loop closes (the e2e test drives
    it up from the random-init ~1/vocab base rate)."""
    motif = int(motif)

    def reward(prompt, completion):
        comp = np.asarray(completion)
        return float((comp == motif).mean()) if comp.size else 0.0
    return reward


class FlywheelLoop:
    """Drives train→publish→generate→learn on one model.

    cfg/params: a `models.gpt` config and (optionally) initial params.
    prompt_fn(rng) -> token-id sequence; reward_fn(prompt, completion)
    -> float. The engine is built internally from `engine_kwargs`
    (slots/max_len/block_size/spec/...) on its OWN copy of the initial
    weights — `update_params` donates the engine's buffers, so it must
    not share them with the trainer — or pass a live `engine`.

    `publish_to` takes extra targets every publish also reaches: objects
    with `.update_params(params)` (engines, `InferenceReplica`s) are
    called directly; serve `DeploymentHandle`s go through the
    `handle.update_params.remote(host_params)` method sugar — the serve
    path to remote replicas.

    `run(iterations)` returns `(state, per-step host metrics)`;
    `self.history` holds one host-side record per iteration
    (reward_mean, rollout_tok_s, staleness = engine version minus the
    oldest tag in the batch)."""

    def __init__(self, cfg, prompt_fn, reward_fn, *, params=None,
                 seed: int = 0, engine=None, engine_kwargs=None,
                 mesh=None, optimizer=None, lr: float = 1e-2,
                 clip: float | None = 0.2, baseline_decay: float = 0.8,
                 prompts_per_iter: int = 4, max_new_tokens: int = 6,
                 temperature: float = 1.0, pad_to: int | None = None,
                 publish_every: int = 1, publish_to=()):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.serve.engine import InferenceEngine
        from ray_tpu.train.loop import TrainLoop
        from ray_tpu.train.spmd import TrainState
        self._jax, self._jnp = jax, jnp
        self.cfg = cfg
        self.prompt_fn, self.reward_fn = prompt_fn, reward_fn
        self.prompts_per_iter = int(prompts_per_iter)
        self.publish_every = max(1, int(publish_every))
        self._publish_targets = list(publish_to)
        self._rng = np.random.default_rng(seed)
        self._baseline: float | None = None
        self._decay = float(baseline_decay)
        self.history: list[dict] = []
        self.published_version = 0

        if params is None:
            params = gpt_init(cfg, seed)
        if engine is None:
            engine = InferenceEngine(
                jax.tree.map(jnp.copy, params), cfg, mesh=mesh,
                **(engine_kwargs or {}))
        self.engine = engine
        self.sampler = EngineSampler(
            engine, max_new_tokens=max_new_tokens,
            temperature=temperature, pad_to=pad_to)
        W = int(max_new_tokens)
        optimizer = optimizer if optimizer is not None else optax.adam(lr)

        from ray_tpu.models import gpt

        def loss_fn(p, batch):
            lp = gpt.completion_logprobs(
                p, batch["tokens"], batch["start"], W, cfg, mesh)
            ratio = jnp.exp(lp - batch["behavior_logp"])
            adv = batch["advantage"][:, None]
            if clip is None:
                surr = lp * adv        # REINFORCE-with-baseline
            else:
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
            m = batch["mask"]
            denom = jnp.maximum(m.sum(), 1.0)
            return -(surr * m).sum() / denom, (lp, ratio, m, denom)

        def step_fn(state, batch):
            grad = jax.value_and_grad(loss_fn, has_aux=True)
            (loss, (lp, ratio, m, denom)), grads = grad(
                state.params, batch)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics = {
                "loss": loss,
                "mean_logprob": (lp * m).sum() / denom,
                "mean_ratio": (ratio * m).sum() / denom,
            }
            return (TrainState(new_params, opt_state, state.step + 1),
                    metrics)

        self._step = jax.jit(step_fn, donate_argnums=(0,))
        self.state = TrainState(params, optimizer.init(params),
                                jnp.zeros((), jnp.int32))
        self.loop = TrainLoop(self._step, publisher=self._publish)
        # Flywheel-staleness series on /metrics: the telemetry bridge
        # republishes stats() (last iteration's history record) at every
        # scrape. Weakref registration — nothing pins this loop alive.
        from ray_tpu.util import telemetry as _telemetry
        self.name = _telemetry.next_name("flywheel")
        _telemetry.register_stats_source(self.name, self,
                                         kind="flywheel")

    # -- publish side ---------------------------------------------------

    def _publish(self, state, step: int):
        if step % self.publish_every:
            return
        self.published_version = self.engine.update_params(state.params)
        host = None
        for t in self._publish_targets:
            up = getattr(t, "update_params", None)
            if up is None:
                continue
            if hasattr(up, "remote"):   # serve DeploymentHandle sugar
                if host is None:
                    # graftlint: disable-next-line=R001 host copy made only for remote serve-handle targets, between dispatches (the publisher runs at the donation-safety point, never inside a step)
                    host = self._jax.tree.map(np.asarray, state.params)
                up.remote(host)
            else:
                up(state.params)

    # -- generate side --------------------------------------------------

    def _collect(self):
        """One engine rollout -> device batch for the jitted step, plus
        the host-side history record."""
        jnp = self._jnp
        prompts = [self.prompt_fn(self._rng)
                   for _ in range(self.prompts_per_iter)]
        batch = self.sampler.rollout(prompts, self.reward_fn)
        r = batch[sb.REWARDS]
        mean_r = float(r.mean())
        if self._baseline is None:
            self._baseline = mean_r
        adv = (r - self._baseline).astype(np.float32)
        self._baseline = (self._decay * self._baseline
                          + (1.0 - self._decay) * mean_r)
        live = batch[MASK] > 0
        oldest = (int(batch[PARAMS_VERSION][live].min())
                  if live.any() else self.engine.params_version)
        self.history.append({
            "reward_mean": mean_r,
            "baseline": self._baseline,
            "staleness": self.engine.params_version - oldest,
            "engine_version": self.engine.params_version,
            "rollout_tok_s": self.sampler.last_rollout_tok_s,
        })
        return {
            "tokens": jnp.asarray(batch[TOKENS]),
            "start": jnp.asarray(batch[START]),
            "behavior_logp": jnp.asarray(batch[sb.ACTION_LOGP]),
            "mask": jnp.asarray(batch[MASK]),
            "advantage": jnp.asarray(adv),
        }

    # -- introspection --------------------------------------------------

    def stats(self) -> dict:
        """Host-side flywheel health: iteration count plus the last
        history record (reward, baseline, staleness, engine version,
        rollout rate) — what the telemetry bridge tags as flywheel_*."""
        last = self.history[-1] if self.history else {}
        return {
            "iterations": len(self.history),
            "published_version": self.published_version,
            "reward_mean": last.get("reward_mean", 0.0),
            "baseline": last.get("baseline", 0.0),
            "staleness": last.get("staleness", 0),
            "engine_version": last.get(
                "engine_version", self.engine.params_version),
            "rollout_tok_s": last.get("rollout_tok_s", 0.0),
        }

    # -- drive ----------------------------------------------------------

    def run(self, iterations: int):
        """Alternate generate/train/publish for `iterations` cycles
        through `TrainLoop.run` (generation rides the lazy batch
        iterator, publication the `publisher` hook). Returns
        (final TrainState, per-step host metrics)."""
        it = (self._collect() for _ in range(int(iterations)))
        self.state, metrics = self.loop.run(self.state, it,
                                            num_steps=int(iterations))
        return self.state, metrics


def gpt_init(cfg, seed: int):
    import jax
    from ray_tpu.models import gpt
    return gpt.init_params(jax.random.PRNGKey(seed), cfg)
