"""Job submission: run driver scripts as managed subprocesses.

Counterpart of the reference's job layer
(`dashboard/modules/job/job_manager.py:508` JobManager — `submit_job` :823
spawns the entrypoint as a subprocess `_exec_entrypoint` :208, tracks
JobStatus, captures logs; SDK `job/sdk.py:40` JobSubmissionClient). The
manager lives in the driver/NodeServer process; external processes reach
it through the control channel (CLI `job submit/...`) or HTTP
(dashboard module). Each job runs `entrypoint` as a shell command whose
own `ray_tpu.init()` creates an independent session, exactly like
reference jobs start their own driver.
"""

from __future__ import annotations

import os
import re
import shlex
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    # PENDING (recorded, exec not attempted) -> STARTING (exec attempted,
    # pid not yet durable) -> RUNNING -> SUCCEEDED | FAILED | STOPPED
    status: str = "PENDING"
    submitted_ts: float = field(default_factory=time.time)
    finished_ts: Optional[float] = None
    returncode: Optional[int] = None
    metadata: dict = field(default_factory=dict)
    pid: Optional[int] = None
    # /proc start time of pid, so recovery can tell the job's process
    # from an unrelated one that reused the pid
    pid_start: Optional[int] = None
    runtime_env: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)


_JOB_ID_RE = re.compile(r"[A-Za-z0-9_.-]+")


def _proc_start(pid: int) -> Optional[int]:
    """Kernel start time (clock ticks since boot) of `pid`, or None if the
    process is gone — the pid-reuse-proof identity (proc/<pid>/stat f22)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read().decode(errors="replace")
        # field 2 (comm) may contain spaces/parens; fields after the
        # closing paren are well-formed
        return int(data.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


class JobManager:
    """Job table is PERSISTED (one json per job under log_dir) so a
    restarted standalone head re-adopts in-flight jobs: they run in their
    own process groups (start_new_session) and record their exit status to
    an .rc file, surviving a head crash (reference: the job table lives in
    GCS and job drivers are independent processes, job_manager.py:508)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._recover()

    # -- persistence ----------------------------------------------------

    def _info_path(self, job_id: str) -> str:
        return os.path.join(self.log_dir, f"{job_id}.json")

    def _rc_path(self, job_id: str) -> str:
        return os.path.join(self.log_dir, f"{job_id}.rc")

    def _pid_path(self, job_id: str) -> str:
        return os.path.join(self.log_dir, f"{job_id}.pid")

    def _read_pid(self, job_id: str) -> Optional[tuple]:
        """(pid, start_ticks) the wrapper recorded, or None. start_ticks
        is the pid-reuse-proof identity (/proc/<pid>/stat f22)."""
        try:
            with open(self._pid_path(job_id)) as f:
                pid_s, start_s = f.read().split()
                return int(pid_s), int(start_s)
        except (OSError, ValueError):
            return None

    def _persist(self, info: JobInfo) -> None:
        import json
        tmp = self._info_path(info.job_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info.to_dict(), f)
        os.replace(tmp, self._info_path(info.job_id))

    def _recover(self) -> None:
        import glob as _glob
        import json
        for path in _glob.glob(os.path.join(self.log_dir, "*.json")):
            try:
                with open(path) as f:
                    info = JobInfo(**json.load(f))
            except (OSError, TypeError, ValueError):
                continue
            self._jobs[info.job_id] = info
            if info.status == "PENDING":
                # recorded but exec never ATTEMPTED (status flips to
                # STARTING before Popen): safe to run now
                self._exec(info)
            elif info.status == "STARTING":
                # head died inside the launch window. The wrapper writes
                # its pid to a durable file as its very first act, so:
                # rc landed -> finalize; pid landed and alive -> adopt;
                # otherwise the process never got as far as the pid file
                # (or died before writing rc) -> FAILED. Re-running could
                # double-execute a non-idempotent entrypoint, never that.
                rc = self._read_rc(info.job_id)
                rec = self._read_pid(info.job_id)
                live = rec is not None and _proc_start(rec[0]) == rec[1]
                if rc is not None:
                    self._finalize(info.job_id, rc)
                elif live:
                    with self._lock:
                        info.status = "RUNNING"
                        info.pid, info.pid_start = rec
                    self._persist(info)
                    self._adopt(info)
                else:
                    # close the race where the wrapper wrote its rc (and
                    # exited) between the first rc read and the liveness
                    # check — a successful exit must not be marked FAILED
                    rc = self._read_rc(info.job_id)
                    if rc is not None:
                        self._finalize(info.job_id, rc)
                        continue
                    with self._lock:
                        info.status = "FAILED"
                        info.finished_ts = time.time()
                    self._persist(info)
            elif info.status == "RUNNING":
                self._adopt(info)

    def _adopt(self, info: JobInfo) -> None:
        """Re-watch a job that outlived the previous head incarnation."""
        def alive() -> bool:
            if info.pid is None:
                return False
            start = _proc_start(info.pid)
            # start-time mismatch = the pid was recycled by another
            # process; the job itself is gone
            return start is not None and start == info.pid_start

        def watch():
            while True:
                rc = self._read_rc(info.job_id)
                if rc is not None:
                    self._finalize(info.job_id, rc)
                    return
                if not alive():
                    # process gone and no rc recorded: crashed
                    self._finalize(info.job_id, None)
                    return
                from ray_tpu._private.constants import JOB_ADOPT_POLL_S
                time.sleep(JOB_ADOPT_POLL_S)
        threading.Thread(target=watch, daemon=True).start()

    def _read_rc(self, job_id: str) -> Optional[int]:
        try:
            with open(self._rc_path(job_id)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    # -- lifecycle ------------------------------------------------------

    def submit(self, entrypoint: str, *, job_id: str | None = None,
               runtime_env: dict | None = None,
               metadata: dict | None = None) -> str:
        job_id = job_id or f"job_{uuid.uuid4().hex[:12]}"
        # job_id lands in file paths and (quoted) shell text; constrain it
        # so neither can be abused (reference: submission IDs are opaque)
        if not _JOB_ID_RE.fullmatch(job_id):
            raise ValueError(
                f"invalid job_id {job_id!r}: must match [A-Za-z0-9_.-]+")
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            info = JobInfo(job_id, entrypoint, metadata=metadata or {},
                           runtime_env=runtime_env or {})
            self._jobs[job_id] = info
        self._persist(info)
        self._exec(info)
        return job_id

    def _exec(self, info: JobInfo) -> None:
        job_id, runtime_env = info.job_id, info.runtime_env
        # jobs resolve the same modules as the cluster's own processes
        # (uninstalled checkouts included), like worker spawns do
        from ray_tpu._private.spawn import propagate_pythonpath
        env = propagate_pythonpath(dict(os.environ))
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        env["RAY_TPU_JOB_ID"] = job_id
        # job drivers stream their workers' output into the job log by
        # default (reference: jobs run with log_to_driver on)
        env.setdefault("RAY_TPU_LOG_TO_DRIVER", "1")
        # durable launch intent BEFORE Popen: recovery must never re-exec
        # a maybe-started job (exactly-once on the pessimistic side)
        with self._lock:
            info.status = "STARTING"
        self._persist(info)
        log_path = self.log_path(job_id)
        logf = open(log_path, "ab")
        # subshell + rc file: the exit status survives a head restart
        # (a restarted head is no longer the parent and cannot wait())
        # pid file first: a restarted head can adopt (or kill) the
        # process group even if the head died between Popen and _persist.
        # Start ticks ride along as the pid-reuse-proof identity (the
        # wrapper is /bin/sh, comm has no spaces, so f22 is field 22).
        wrapped = (f"echo $$ $(awk '{{print $22}}' /proc/$$/stat) "
                   f"> {shlex.quote(self._pid_path(job_id))}; "
                   f"({info.entrypoint}); _rc=$?; "
                   f"echo $_rc > {shlex.quote(self._rc_path(job_id))}; "
                   f"exit $_rc")
        try:
            proc = subprocess.Popen(
                wrapped, shell=True, stdout=logf, stderr=subprocess.STDOUT,
                env=env, start_new_session=True,
                cwd=runtime_env.get("working_dir") or None)
        except OSError as e:
            logf.close()
            with self._lock:
                info.status = "FAILED"
                info.finished_ts = time.time()
            self._persist(info)
            raise RuntimeError(f"failed to exec job: {e}") from e
        with self._lock:
            info.status = "RUNNING"
            info.pid = proc.pid
            info.pid_start = _proc_start(proc.pid)
            self._procs[job_id] = proc
        self._persist(info)
        threading.Thread(target=self._wait, args=(job_id, proc, logf),
                         daemon=True).start()

    def _wait(self, job_id: str, proc: subprocess.Popen, logf):
        rc = proc.wait()
        logf.close()
        self._finalize(job_id, rc)

    def _finalize(self, job_id: str, rc: Optional[int]):
        with self._lock:
            info = self._jobs[job_id]
            if info.status not in ("STOPPED",):
                if rc == 0:
                    info.status = "SUCCEEDED"
                else:
                    info.status = "FAILED"
            info.returncode = rc
            info.finished_ts = time.time()
            self._procs.pop(job_id, None)
        self._persist(info)

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            if info.status not in ("PENDING", "STARTING", "RUNNING"):
                return False     # already finished; nothing to signal
            pid = proc.pid if proc is not None else info.pid
            if pid is None:
                return False
            info.status = "STOPPED"
        self._persist(info)
        try:
            # the job runs in its own process group (start_new_session)
            os.killpg(pid, 15)
        except OSError:
            pass
        return True

    def status(self, job_id: str) -> dict:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            return info.to_dict()

    def list(self) -> list[dict]:
        with self._lock:
            return [j.to_dict() for j in self._jobs.values()]

    def log_path(self, job_id: str) -> str:
        return os.path.join(self.log_dir, f"{job_id}.log")

    def logs(self, job_id: str, tail_bytes: int = 1 << 20) -> str:
        path = self.log_path(job_id)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""


class JobSubmissionClient:
    """Client API (reference: `job/sdk.py:40`) — works in-process against
    the current session, or attached to another session's socket."""

    def __init__(self, session_dir: str | None = None):
        if session_dir is None:
            from ray_tpu._private import worker as _worker
            self._control = _worker.get_client().control
        else:
            from ray_tpu._private.attach import AttachClient
            self._control = AttachClient(session_dir).control

    def submit_job(self, *, entrypoint: str, job_id: str | None = None,
                   runtime_env: dict | None = None,
                   metadata: dict | None = None) -> str:
        return self._control("job_submit", {
            "entrypoint": entrypoint, "job_id": job_id,
            "runtime_env": runtime_env, "metadata": metadata})

    def get_job_status(self, job_id: str) -> str:
        return self._control("job_status", job_id)["status"]

    def get_job_info(self, job_id: str) -> dict:
        return self._control("job_status", job_id)

    def list_jobs(self) -> list[dict]:
        return self._control("job_list")

    def get_job_logs(self, job_id: str) -> str:
        return self._control("job_logs", job_id)

    def stop_job(self, job_id: str) -> bool:
        return self._control("job_stop", job_id)

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(job_id)
            if st in ("SUCCEEDED", "FAILED", "STOPPED"):
                return st
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still {st!r} after {timeout}s")
