"""Job submission: run driver scripts as managed subprocesses.

Counterpart of the reference's job layer
(`dashboard/modules/job/job_manager.py:508` JobManager — `submit_job` :823
spawns the entrypoint as a subprocess `_exec_entrypoint` :208, tracks
JobStatus, captures logs; SDK `job/sdk.py:40` JobSubmissionClient). The
manager lives in the driver/NodeServer process; external processes reach
it through the control channel (CLI `job submit/...`) or HTTP
(dashboard module). Each job runs `entrypoint` as a shell command whose
own `ray_tpu.init()` creates an independent session, exactly like
reference jobs start their own driver.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = "PENDING"   # PENDING RUNNING SUCCEEDED FAILED STOPPED
    submitted_ts: float = field(default_factory=time.time)
    finished_ts: Optional[float] = None
    returncode: Optional[int] = None
    metadata: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)


class JobManager:
    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}

    def submit(self, entrypoint: str, *, job_id: str | None = None,
               runtime_env: dict | None = None,
               metadata: dict | None = None) -> str:
        job_id = job_id or f"job_{uuid.uuid4().hex[:12]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            info = JobInfo(job_id, entrypoint, metadata=metadata or {})
            self._jobs[job_id] = info
        env = dict(os.environ)
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[str(k)] = str(v)
        env["RAY_TPU_JOB_ID"] = job_id
        log_path = self.log_path(job_id)
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=logf, stderr=subprocess.STDOUT,
                env=env, start_new_session=True,
                cwd=(runtime_env or {}).get("working_dir") or None)
        except OSError as e:
            logf.close()
            with self._lock:
                info.status = "FAILED"
                info.finished_ts = time.time()
            raise RuntimeError(f"failed to exec job: {e}") from e
        with self._lock:
            info.status = "RUNNING"
            self._procs[job_id] = proc
        threading.Thread(target=self._wait, args=(job_id, proc, logf),
                         daemon=True).start()
        return job_id

    def _wait(self, job_id: str, proc: subprocess.Popen, logf):
        rc = proc.wait()
        logf.close()
        with self._lock:
            info = self._jobs[job_id]
            if info.status != "STOPPED":
                info.status = "SUCCEEDED" if rc == 0 else "FAILED"
            info.returncode = rc
            info.finished_ts = time.time()
            self._procs.pop(job_id, None)

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            if proc is None:
                return False
            info.status = "STOPPED"
        try:
            # the job runs in its own process group (start_new_session)
            os.killpg(proc.pid, 15)
        except OSError:
            pass
        return True

    def status(self, job_id: str) -> dict:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            return info.to_dict()

    def list(self) -> list[dict]:
        with self._lock:
            return [j.to_dict() for j in self._jobs.values()]

    def log_path(self, job_id: str) -> str:
        return os.path.join(self.log_dir, f"{job_id}.log")

    def logs(self, job_id: str, tail_bytes: int = 1 << 20) -> str:
        path = self.log_path(job_id)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""


class JobSubmissionClient:
    """Client API (reference: `job/sdk.py:40`) — works in-process against
    the current session, or attached to another session's socket."""

    def __init__(self, session_dir: str | None = None):
        if session_dir is None:
            from ray_tpu._private import worker as _worker
            self._control = _worker.get_client().control
        else:
            from ray_tpu._private.attach import AttachClient
            self._control = AttachClient(session_dir).control

    def submit_job(self, *, entrypoint: str, job_id: str | None = None,
                   runtime_env: dict | None = None,
                   metadata: dict | None = None) -> str:
        return self._control("job_submit", {
            "entrypoint": entrypoint, "job_id": job_id,
            "runtime_env": runtime_env, "metadata": metadata})

    def get_job_status(self, job_id: str) -> str:
        return self._control("job_status", job_id)["status"]

    def get_job_info(self, job_id: str) -> dict:
        return self._control("job_status", job_id)

    def list_jobs(self) -> list[dict]:
        return self._control("job_list")

    def get_job_logs(self, job_id: str) -> str:
        return self._control("job_logs", job_id)

    def stop_job(self, job_id: str) -> bool:
        return self._control("job_stop", job_id)

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(job_id)
            if st in ("SUCCEEDED", "FAILED", "STOPPED"):
                return st
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still {st!r} after {timeout}s")
