"""Cloud-node lifecycle interface + in-memory fake.

Counterpart of the reference's `autoscaler/node_provider.py` (abstract
`NodeProvider`: `create_node`, `terminate_node`, `non_terminated_nodes`,
`node_tags`, …) and the fake used for autoscaler e2e tests without a cloud
(`_private/fake_multi_node/node_provider.py:237` FakeMultiNodeProvider).
A real deployment implements this against the TPU-VM API (the reference's
`gcp/` provider is the template); the framework only depends on the verbs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

TAG_NODE_KIND = "ray_tpu-node-kind"      # "head" | "worker"
TAG_NODE_TYPE = "ray_tpu-user-node-type"
TAG_NODE_STATUS = "ray_tpu-node-status"  # "pending" | "up-to-date"


class NodeProvider:
    """Minimal lifecycle verbs the autoscaler needs."""

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_config: dict, tags: Dict[str, str],
                    count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def drain_node(self, node_id: str) -> None:
        """Advisory pre-termination hook: stop scheduling onto the node
        and let in-flight work finish. The autoscaler calls this BEFORE
        every `terminate_node` (reference: the GCS DrainNode RPC the
        reference autoscaler issues ahead of instance teardown).
        Default: no-op for providers with nothing to drain."""

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> Optional[str]:
        return None


def make_node_provider(spec: dict | None, node_server=None) -> NodeProvider:
    """Build a provider from a config spec. The head constructs providers
    from `attach_autoscaler` payloads — provider INSTANCES never cross a
    process boundary, so cluster YAML / control payloads carry
    `provider: {type: ..., ...}` instead (reference: `provider.type` in
    cluster configs resolved by `autoscaler/_private/providers.py`)."""
    spec = dict(spec or {})
    kind = spec.pop("type", "local")
    if kind == "local":
        return LocalDaemonNodeProvider(node_server)
    if kind == "fake":
        return FakeNodeProvider(float(spec.get("startup_delay_s", 0.0)))
    if kind == "gcp-tpu":
        from ray_tpu.autoscaler.gcp_tpu import TpuVmNodeProvider
        cluster = spec.pop("cluster_name", "default")
        return TpuVmNodeProvider(spec, cluster_name=cluster)
    raise ValueError(f"unknown node provider type {kind!r}")


class LocalDaemonNodeProvider(NodeProvider):
    """Launches REAL HostDaemon processes on this machine — the e2e
    provider behind the closed autoscaler loop (counterpart of the
    reference's FakeMultiNodeProvider,
    `_private/fake_multi_node/node_provider.py:237`, which spawns real
    raylets locally so the autoscaler can be tested without a cloud).
    Provider node ids ARE cluster node ids."""

    def __init__(self, node_server):
        self._node = node_server
        self._lock = threading.Lock()
        self._tags: dict[str, dict] = {}     # node_id -> tags

    def _alive(self, node_id: str) -> bool:
        n = self._node.nodes.get(node_id)
        return n is not None and n.alive

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            return [nid for nid, tags in self._tags.items()
                    if self._alive(nid)
                    and all(tags.get(k) == v
                            for k, v in tag_filters.items())]

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._tags.get(node_id, {}))

    def create_node(self, node_config: dict, tags: Dict[str, str],
                    count: int) -> None:
        resources = dict(node_config.get("resources") or {"CPU": 1.0})
        num_tpus = int(node_config.get("num_tpus", 0))
        for _ in range(count):
            nid = self._node.add_node(resources, num_tpus)
            with self._lock:
                self._tags[nid] = {**tags, TAG_NODE_STATUS: "up-to-date"}

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._tags.pop(node_id, None)
        self._node.kill_node(node_id, force=False)   # graceful KillNode

    def is_running(self, node_id: str) -> bool:
        return self._alive(node_id)

    def internal_ip(self, node_id: str) -> Optional[str]:
        return "127.0.0.1"


class FakeNodeProvider(NodeProvider):
    """Instant in-memory nodes (optionally with a simulated startup delay)
    for autoscaler tests — the reference's fake-multinode trick."""

    def __init__(self, startup_delay_s: float = 0.0):
        self._lock = threading.Lock()
        self._next_id = 0
        self._nodes: dict[str, dict] = {}   # id -> {tags, created_ts}
        self.startup_delay_s = startup_delay_s
        self.created_log: list[tuple] = []   # (node_type, count)
        self.terminated_log: list[str] = []
        # ordered verb log: ("drain"|"terminate", node_id) — tests
        # assert drain happens strictly before terminate per node
        self.event_log: list[tuple] = []

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for nid, info in self._nodes.items():
                if all(info["tags"].get(k) == v
                       for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def create_node(self, node_config: dict, tags: Dict[str, str],
                    count: int) -> None:
        with self._lock:
            self.created_log.append((tags.get(TAG_NODE_TYPE), count))
            for _ in range(count):
                nid = f"node-{self._next_id}"
                self._next_id += 1
                self._nodes[nid] = {
                    "tags": dict(tags), "created_ts": time.time()}

    def drain_node(self, node_id: str) -> None:
        with self._lock:
            self.event_log.append(("drain", node_id))

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            self.terminated_log.append(node_id)
            self.event_log.append(("terminate", node_id))

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return False
            return time.time() - info["created_ts"] >= self.startup_delay_s

    def internal_ip(self, node_id: str) -> Optional[str]:
        return "127.0.0.1"
