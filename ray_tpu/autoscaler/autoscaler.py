"""StandardAutoscaler: reconcile node count with demand each update().

Counterpart of the reference's `autoscaler/_private/autoscaler.py:166`
(`StandardAutoscaler.update` :368): each tick it (1) reads the node list
from the provider, (2) terminates workers idle beyond the timeout or in
excess of max_workers, (3) asks the demand scheduler what to launch, and
(4) launches in bounded batches. The head-side Monitor loop
(`_private/monitor.py:371`) becomes whatever driver loop calls update()
periodically — test code calls it directly, like the reference's
autoscaler unit tests.
"""

from __future__ import annotations

import logging
from typing import Dict

from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import (
    TAG_NODE_KIND,
    TAG_NODE_STATUS,
    TAG_NODE_TYPE,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler,
)

logger = logging.getLogger(__name__)

DEFAULT_CONFIG = {
    "max_workers": 8,
    "idle_timeout_minutes": 5.0,
    "max_launch_batch": 5,
    "available_node_types": {},
    # name of the type used when a demand fits nothing (None = error)
}


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, config: dict,
                 load_metrics: LoadMetrics):
        self.provider = provider
        self.config = {**DEFAULT_CONFIG, **config}
        self.load_metrics = load_metrics
        self.scheduler = ResourceDemandScheduler(
            self.config["available_node_types"],
            self.config["max_workers"])
        self.infeasible_gangs: list = []

    # -- helpers ------------------------------------------------------------

    def _workers(self) -> list[str]:
        return self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: "worker"})

    def _workers_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in self._workers():
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            counts[t] = counts.get(t, 0) + 1
        return counts

    # -- main loop body ------------------------------------------------------

    def update(self) -> None:
        workers = self._workers()

        # 1) terminate idle workers past the timeout, but never below a
        # type's min_workers (reference: autoscaler.py idle termination)
        idle_cutoff = self.config["idle_timeout_minutes"] * 60.0
        counts = self._workers_by_type()
        for nid in list(workers):
            ntype = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            spec = self.config["available_node_types"].get(ntype, {})
            if counts.get(ntype, 0) <= spec.get("min_workers", 0):
                continue
            if (nid in self.load_metrics.static_resources
                    and self.load_metrics.idle_seconds(nid) > idle_cutoff):
                logger.info("terminating idle node %s (%s)", nid, ntype)
                self.provider.drain_node(nid)
                self.provider.terminate_node(nid)
                self.load_metrics.remove_node(nid)
                counts[ntype] = counts.get(ntype, 0) - 1

        # 2) enforce global max_workers (scale-down on config change)
        workers = self._workers()
        excess = len(workers) - self.config["max_workers"]
        for nid in workers[:max(0, excess)]:
            logger.info("terminating excess node %s", nid)
            self.provider.drain_node(nid)
            self.provider.terminate_node(nid)
            self.load_metrics.remove_node(nid)

        # 3) launch for unmet demand
        avail = [dict(a) for a
                 in self.load_metrics.available_resources.values()]
        to_launch, infeasible = self.scheduler.get_nodes_to_launch(
            self._workers_by_type(), avail,
            self.load_metrics.pending_demands,
            self.load_metrics.pending_gangs)
        self.infeasible_gangs = infeasible
        for ntype, count in to_launch.items():
            spec = self.config["available_node_types"][ntype]
            batch = min(count, self.config["max_launch_batch"])
            logger.info("launching %d x %s", batch, ntype)
            self.provider.create_node(
                spec.get("node_config", {}),
                {TAG_NODE_KIND: "worker", TAG_NODE_TYPE: ntype,
                 TAG_NODE_STATUS: "pending"},
                batch)
