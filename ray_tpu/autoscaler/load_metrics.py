"""Cluster load view consumed by the autoscaler.

Counterpart of the reference's `autoscaler/_private/load_metrics.py`
(LoadMetrics: per-node resource totals/availability, pending resource
demands, placement-group gang demands, last-used timestamps), which the
head-side Monitor fills from GCS resource reports
(`_private/monitor.py:249` update_load_metrics).
"""

from __future__ import annotations

import time
from typing import Dict, List


class LoadMetrics:
    def __init__(self):
        # node_id -> static/dynamic resources
        self.static_resources: Dict[str, dict] = {}
        self.available_resources: Dict[str, dict] = {}
        self.last_used: Dict[str, float] = {}
        # flat list of unschedulable task/actor demands: [{"CPU": 1}, ...]
        self.pending_demands: List[dict] = []
        # gang demands: list of bundle-lists, each gang must co-schedule
        # (STRICT_PACK placement groups / SPMD slices)
        self.pending_gangs: List[List[dict]] = []

    def update_node(self, node_id: str, static: dict, available: dict,
                    busy: bool) -> None:
        self.static_resources[node_id] = dict(static)
        self.available_resources[node_id] = dict(available)
        if busy or node_id not in self.last_used:
            self.last_used[node_id] = time.time()

    def remove_node(self, node_id: str) -> None:
        self.static_resources.pop(node_id, None)
        self.available_resources.pop(node_id, None)
        self.last_used.pop(node_id, None)

    def set_demands(self, demands: List[dict],
                    gangs: List[List[dict]] | None = None) -> None:
        self.pending_demands = [dict(d) for d in demands]
        self.pending_gangs = [[dict(b) for b in g] for g in (gangs or [])]

    def idle_seconds(self, node_id: str) -> float:
        # setdefault, not get: a node we have never seen a report for
        # starts its idle clock NOW and accrues from here — with a plain
        # get() each call re-reads time.time() as the baseline, so such
        # a node reads 0 forever and can never be idle-terminated.
        last = self.last_used.setdefault(node_id, time.time())
        return time.time() - last


def replica_demands_from_engine_stats(
        stats: List[dict], *,
        target_queue_depth: float = 2.0,
        resources_per_replica: dict | None = None) -> List[dict]:
    """Translate serve-engine load stats into autoscaler demand entries.

    Each stats dict is one `InferenceEngine.stats()` (as published
    through `Replica.stats`); requests waiting behind a saturated
    engine (`queue_depth`, plus any overflow of `pending` admissions)
    become synthetic replica-shaped resource demands — one demand per
    `target_queue_depth` queued requests, rounded up — suitable for
    `LoadMetrics.set_demands`, closing the serve→autoscaler loop."""
    res = dict(resources_per_replica or {"CPU": 1.0})
    demands: List[dict] = []
    tq = max(float(target_queue_depth), 1e-6)
    for s in stats:
        queued = float(s.get("queue_depth", 0) or 0)
        n = int(-(-queued // tq))   # ceil
        demands.extend(dict(res) for _ in range(n))
    return demands
