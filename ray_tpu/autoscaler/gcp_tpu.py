"""GCE TPU-VM node provider — `ray-tpu up` provisions real slices.

Counterpart of the reference's GCP provider
(`autoscaler/_private/gcp/node_provider.py:59` GCPNodeProvider,
`gcp/node.py:547` GCPTPU resource wrapper, `gcp/config.py` bootstrap),
redesigned around the one property that matters for TPU clusters: a TPU
slice is ONE API resource (`projects.locations.nodes`) that the platform
materializes as N hosts atomically. Gang semantics (SURVEY §7.4#3,
"whole-slice atomicity") therefore fall out of the API: one
`create_node` call per slice either yields every host of a v5e-16 or
nothing — there is no partial-slice state to reconcile, unlike the
reference's per-instance GCE path.

Transport: the provider speaks the TPU REST surface
(https://tpu.googleapis.com/v2) through an injectable `HttpClient` so
tests (and air-gapped environments) can point it at a fake server with
`provider: {type: gcp-tpu, api_endpoint: "http://127.0.0.1:PORT"}`.
Auth is resolved lazily: explicit token in the provider config, then
`google.auth` application-default credentials, then the GCE metadata
server — never at import time.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (
    TAG_NODE_STATUS,
    NodeProvider,
)

logger = logging.getLogger("ray_tpu.gcp_tpu")

# TPU node lifecycle states (REST: projects.locations.nodes.state).
# "non-terminated" for the autoscaler's purposes = anything that still
# holds (or will hold) capacity; PREEMPTED/TERMINATED slices are gone.
_LIVE_STATES = frozenset({
    "CREATING", "READY", "RESTARTING", "REPAIRING", "STARTING", "STOPPED",
    "STOPPING",
})

# GCP label values: lowercase letters, digits, dash/underscore, <=63.
_LABEL_SANITIZE = re.compile(r"[^a-z0-9_-]")

# transient HTTP statuses worth retrying (quota, races, server blips)
_RETRY_STATUSES = frozenset({429, 500, 502, 503})


def _to_label(value: str) -> str:
    return _LABEL_SANITIZE.sub("-", str(value).lower())[:63]


class HttpClient:
    """Minimal JSON-over-HTTP seam. Tests substitute their own instance
    (or just an `api_endpoint` at a fake server); prod uses this one."""

    def __init__(self, token_source=None):
        self._token_source = token_source
        self._creds = None          # cached google.auth credentials
        self._meta_token = None     # (token, expiry_ts) via metadata

    def request(self, method: str, url: str, body: dict | None = None,
                timeout: float = 30.0):
        """-> (status_code, parsed_json_or_{}). Network errors raise."""
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        tok = self._token()
        if tok:
            req.add_header("Authorization", f"Bearer {tok}")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = resp.read()
                return resp.status, (json.loads(payload) if payload else {})
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                parsed = json.loads(payload) if payload else {}
            except ValueError:
                parsed = {"raw": payload.decode("utf-8", "replace")}
            return e.code, parsed

    def _token(self) -> Optional[str]:
        """Bearer token, cached until near expiry — the autoscaler polls
        the API every few seconds and must not pay (or rate-limit) an
        OAuth round trip per request."""
        if self._token_source is not None:
            return self._token_source()
        try:
            if self._creds is None:
                import google.auth
                self._creds, _ = google.auth.default(
                    scopes=["https://www.googleapis.com/auth/"
                            "cloud-platform"])
            if not self._creds.valid:
                import google.auth.transport.requests
                self._creds.refresh(
                    google.auth.transport.requests.Request())
            return self._creds.token
        except Exception:
            self._creds = None
        try:
            tok, exp = self._meta_token or (None, 0)
            if tok and time.time() < exp - 60:
                return tok
            req = urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/"
                "instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                payload = json.loads(resp.read())
            self._meta_token = (payload["access_token"],
                                time.time() + payload.get("expires_in", 0))
            return self._meta_token[0]
        except Exception:
            return None


def bootstrap_gcp_tpu(provider_cfg: dict) -> dict:
    """Validate + default-fill a `provider: {type: gcp-tpu, ...}` block
    (the reference's `gcp/config.py` bootstrap, minus IAM mutation —
    TPU-VM service accounts come pre-scoped; we refuse to silently edit
    project IAM from a laptop). Returns a normalized copy."""
    cfg = dict(provider_cfg)
    missing = [k for k in ("project_id", "zone") if not cfg.get(k)]
    if missing:
        raise ValueError(
            f"provider gcp-tpu requires {missing} (cluster YAML "
            "provider: {type: gcp-tpu, project_id: ..., zone: ...})")
    cfg.setdefault("api_endpoint", "https://tpu.googleapis.com")
    cfg.setdefault("api_version", "v2")
    cfg.setdefault("operation_poll_interval_s", 5.0)
    cfg.setdefault("operation_timeout_s", 1800.0)   # slices take a while
    cfg.setdefault("max_retries", 5)
    return cfg


class TpuVmNodeProvider(NodeProvider):
    """NodeProvider over TPU-VM slices: provider node id == TPU node
    name (the last path segment). One provider node is one SLICE; the
    hosts inside it each run a HostDaemon that joins the head via the
    startup script, so cluster membership can exceed provider-node count
    — the autoscaler reasons in slices, the scheduler in hosts, which is
    exactly the two-level split TPU gang placement wants."""

    def __init__(self, provider_cfg: dict, cluster_name: str = "default",
                 http: HttpClient | None = None):
        cfg = bootstrap_gcp_tpu(provider_cfg)
        self.cfg = cfg
        self.cluster_name = _to_label(cluster_name)
        token = cfg.get("token")
        self.http = http or HttpClient(
            token_source=(lambda: token) if token else None)
        self._base = (f"{cfg['api_endpoint']}/{cfg['api_version']}/projects/"
                      f"{cfg['project_id']}/locations/{cfg['zone']}")
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}    # node name -> last API view
        self._counter = int(time.time()) % 100000

    # ---- REST plumbing ------------------------------------------------

    def _call(self, method: str, path: str, body: dict | None = None):
        """Request with bounded retry on transient statuses; raises
        RuntimeError on terminal API errors."""
        url = f"{self._base}{path}" if path.startswith("/") else path
        delay = 1.0
        for attempt in range(int(self.cfg["max_retries"])):
            status, payload = self.http.request(method, url, body)
            if status < 300:
                return payload
            if status in _RETRY_STATUSES:
                logger.warning("TPU API %s %s -> %s (attempt %d), retrying",
                               method, path, status, attempt + 1)
                time.sleep(delay)
                delay = min(delay * 2, 30.0)
                continue
            raise RuntimeError(
                f"TPU API {method} {path} failed: {status} "
                f"{payload.get('error', payload)}")
        raise RuntimeError(
            f"TPU API {method} {path}: exhausted "
            f"{self.cfg['max_retries']} retries (last status {status})")

    def _wait_operation(self, op: dict) -> dict:
        """Block until a long-running operation completes; returns its
        response. Gang atomicity surfaces here: a slice create either
        finishes READY (all hosts exist) or the operation reports an
        error and NO node remains."""
        name = op.get("name")
        if not name or op.get("done"):
            return self._op_result(op)
        deadline = time.monotonic() + float(self.cfg["operation_timeout_s"])
        # operation names are full resource paths
        url = f"{self.cfg['api_endpoint']}/{self.cfg['api_version']}/{name}"
        while time.monotonic() < deadline:
            op = self._call("GET", url)
            if op.get("done"):
                return self._op_result(op)
            time.sleep(float(self.cfg["operation_poll_interval_s"]))
        raise RuntimeError(f"TPU operation {name} timed out")

    @staticmethod
    def _op_result(op: dict) -> dict:
        if op.get("error"):
            raise RuntimeError(f"TPU operation failed: {op['error']}")
        return op.get("response", {})

    # ---- NodeProvider verbs -------------------------------------------

    def _list_nodes(self) -> list[dict]:
        out, page = [], None
        while True:
            path = "/nodes" + (f"?pageToken={page}" if page else "")
            resp = self._call("GET", path)
            out.extend(resp.get("nodes", []))
            page = resp.get("nextPageToken")
            if not page:
                return out

    @staticmethod
    def _short(name: str) -> str:
        return name.rsplit("/", 1)[-1]

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        want = {_to_label(k): _to_label(v) for k, v in tag_filters.items()}
        out = []
        with self._lock:
            self._cache.clear()
            for n in self._list_nodes():
                if n.get("state") not in _LIVE_STATES:
                    continue
                labels = n.get("labels", {})
                if labels.get("ray-tpu-cluster") != self.cluster_name:
                    continue
                if all(labels.get(k) == v for k, v in want.items()):
                    nid = self._short(n["name"])
                    self._cache[nid] = n
                    out.append(nid)
        return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        n = self._get(node_id)
        return dict(n.get("labels", {})) if n else {}

    def create_node(self, node_config: dict, tags: Dict[str, str],
                    count: int) -> None:
        """One API create per SLICE (gang-atomic). `node_config` carries
        the TPU node body fields (accelerator_type, runtime_version,
        optional startup_script / network / extra body passthrough)."""
        body_base = {
            "acceleratorType": node_config.get(
                "accelerator_type", node_config.get("acceleratorType")),
            "runtimeVersion": node_config.get(
                "runtime_version",
                node_config.get("runtimeVersion", "tpu-ubuntu2204-base")),
        }
        if not body_base["acceleratorType"]:
            raise ValueError(
                "gcp-tpu node_config needs accelerator_type "
                "(e.g. v5litepod-8)")
        extra = node_config.get("body") or {}
        body_base.update(extra)
        # tag round-tripping through GCP labels must be lossless: the
        # autoscaler compares node_tags() values verbatim against config
        # node-type names, so a name that sanitization would rewrite
        # (uppercase, '.') would silently miscount workers and churn
        # real billed slices — refuse it up front
        bad = [v for v in tags.values() if _to_label(str(v)) != str(v)]
        if bad:
            raise ValueError(
                f"gcp-tpu requires label-safe tag values "
                f"(lowercase [a-z0-9_-], <=63 chars); offending: {bad} — "
                "rename the node type in available_node_types")
        labels = {_to_label(k): _to_label(v) for k, v in tags.items()}
        labels["ray-tpu-cluster"] = self.cluster_name
        labels[_to_label(TAG_NODE_STATUS)] = "up-to-date"
        body_base["labels"] = labels
        script = node_config.get("startup_script")
        if not script and self.cfg.get("head_address"):
            # default join path: every host of the slice starts a
            # HostDaemon against the head that launched it. num_tpus
            # omitted -> per-host chip auto-detection; custom resources
            # (anything beyond CPU/TPU) are forwarded explicitly.
            declared = dict(node_config.get("resources") or {})
            custom = {k: v for k, v in declared.items()
                      if k not in ("CPU", "TPU")}
            ntpus = node_config.get("num_tpus")
            script = default_startup_script(
                self.cfg["head_address"],
                self.cfg.get("authkey_hex", ""),
                num_tpus=None if ntpus is None else int(ntpus),
                custom_resources=custom or None,
                authkey_secret=self.cfg.get("authkey_secret"))
        if script:
            meta = dict(body_base.get("metadata") or {})
            meta["startup-script"] = script
            body_base["metadata"] = meta
        if node_config.get("preemptible"):
            body_base.setdefault("schedulingConfig", {})["preemptible"] = \
                True
        ops = []
        with self._lock:
            start = self._counter
            self._counter += count
        from ray_tpu.autoscaler.node_provider import TAG_NODE_TYPE
        ntype = labels.get(_to_label(TAG_NODE_TYPE), "worker")
        for i in range(count):
            # resource NAMES are stricter than labels (no underscores)
            node_id = re.sub(r"[^a-z0-9-]", "-", (
                f"ray-tpu-{self.cluster_name}-{ntype}-{start + i}"))
            op = self._call("POST", f"/nodes?nodeId={node_id}", body_base)
            ops.append((node_id, op))
        # block until every slice materializes (or surfaces its error):
        # the autoscaler's update loop is already off-thread, and "create
        # returned" meaning "capacity exists" keeps its accounting honest
        errs = []
        for node_id, op in ops:
            try:
                self._wait_operation(op)
            except RuntimeError as e:
                errs.append(f"{node_id}: {e}")
        if errs:
            raise RuntimeError(
                "slice creation failed: " + "; ".join(errs))

    def terminate_node(self, node_id: str) -> None:
        op = self._call("DELETE", f"/nodes/{node_id}")
        # deletion can run async; the next non_terminated_nodes pass sees
        # DELETING and drops it, so no need to block here
        with self._lock:
            self._cache.pop(node_id, None)

    def is_running(self, node_id: str) -> bool:
        n = self._get(node_id, refresh=True)
        return bool(n) and n.get("state") == "READY"

    def internal_ip(self, node_id: str) -> Optional[str]:
        n = self._get(node_id)
        for ep in (n or {}).get("networkEndpoints", []):
            if ep.get("ipAddress"):
                return ep["ipAddress"]
        return None

    def _get(self, node_id: str, refresh: bool = False) -> Optional[dict]:
        with self._lock:
            cached = self._cache.get(node_id)
        if cached is not None and not refresh:
            return cached
        status, payload = self.http.request(
            "GET", f"{self._base}/nodes/{node_id}")
        if status == 404:
            return None
        if status >= 300:
            raise RuntimeError(
                f"TPU API GET nodes/{node_id} failed: {status}")
        with self._lock:
            self._cache[node_id] = payload
        return payload


def default_startup_script(head_address: str, authkey_hex: str,
                           num_tpus: int | None = None,
                           custom_resources: dict | None = None,
                           extra: str = "",
                           authkey_secret: str | None = None) -> str:
    """Startup script run on EVERY host of the slice: join the head as a
    HostDaemon. The TPU platform executes it per-worker, which is how one
    provider node fans out into N cluster nodes. When `num_tpus` is None
    the host auto-detects its local chips (`start` runs
    `_detect_tpu_chips()` when the flag is absent) — the right default on
    a real TPU-VM; custom resources the node type declared ride along so
    the hosts advertise what the autoscaler planned for.

    Authkey distribution: instance metadata is readable by anyone with
    TPU-node read access on the project, so embedding the authkey there
    exposes cluster control to project readers. When `authkey_secret` is
    set (a Secret Manager resource, `projects/P/secrets/S` — latest
    version is used, or a full `.../versions/N` path) the script instead
    fetches the hex authkey at boot with the VM's own service-account
    token and NOTHING secret lands in metadata; grant the node SA
    `secretmanager.versions.access` on that secret. `authkey_hex` then
    only serves as a fallback for air-gapped test rigs and may be ""."""
    join = (f"python3 -m ray_tpu.scripts.cli start "
            f"--address {head_address}")
    if num_tpus is not None:
        join += f" --num-tpus {int(num_tpus)}"
    if custom_resources:
        import shlex
        join += f" --resources {shlex.quote(json.dumps(custom_resources))}"
    if authkey_secret:
        sec = authkey_secret
        if "/versions/" not in sec:
            sec = sec.rstrip("/") + "/versions/latest"
        # the resource name lands inside a root-run boot script: refuse
        # anything that isn't a plain Secret Manager path (the same
        # strictness node-type tags get above)
        import re
        if not re.fullmatch(
                r"projects/[A-Za-z0-9._-]+/secrets/[A-Za-z0-9._-]+"
                r"/versions/[A-Za-z0-9._-]+", sec):
            raise ValueError(
                f"authkey_secret must look like projects/P/secrets/S"
                f"[/versions/V]; got {authkey_secret!r}")
        # NOTE: plain assignments (not `export VAR=$(...)`) so a failed
        # fetch propagates through set -e instead of booting the host
        # with an empty authkey
        fetch = (
            'TOK=$(curl -s -H "Metadata-Flavor: Google" '
            '"http://metadata.google.internal/computeMetadata/v1/'
            'instance/service-accounts/default/token" '
            "| python3 -c 'import sys,json;"
            'print(json.load(sys.stdin)["access_token"])\')\n'
            f'RAY_TPU_AUTHKEY=$(curl -s -H "Authorization: '
            f'Bearer $TOK" "https://secretmanager.googleapis.com/v1/'
            f'{sec}:access" '
            "| python3 -c 'import sys,json,base64;"
            'print(base64.b64decode(json.load(sys.stdin)["payload"]'
            '["data"]).decode().strip())\')\n'
            'export RAY_TPU_AUTHKEY')
    else:
        fetch = f"export RAY_TPU_AUTHKEY={authkey_hex}"
    return "\n".join([
        "#!/bin/bash",
        "set -e",
        extra or "true",
        fetch,
        join + " --block &",
    ])
