"""Bin-packing of pending resource demands onto node types.

Counterpart of the reference's
`autoscaler/_private/resource_demand_scheduler.py`: given (a) the resources
of currently-running nodes, (b) a flat list of unschedulable demands plus
gang (placement-group) demands, and (c) the configured node types with
min/max counts, decide how many new nodes of each type to launch.

Algorithm, like the reference: first-fit the demands onto existing nodes'
remaining capacity; for what's left, greedily pick the node type that
satisfies the most remaining demand (utility scoring), capped by per-type
and global max_workers. TPU twist: a gang demand (SPMD slice) is
indivisible — all bundles of a gang must fit on ONE node (one ICI domain);
a gang too big for every type is reported as infeasible rather than
silently split across hosts, because XLA collectives can't span a split.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Tuple

_EPS = 1e-9


def _fits(avail: dict, demand: dict) -> bool:
    return all(avail.get(k, 0.0) + _EPS >= v for k, v in demand.items())


def _sub(avail: dict, demand: dict) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class ResourceDemandScheduler:
    def __init__(self, node_types: Dict[str, dict], max_workers: int):
        """node_types: name -> {"resources": {...}, "min_workers": int,
        "max_workers": int}. Counts exclude the head node."""
        self.node_types = node_types
        self.max_workers = max_workers

    def get_nodes_to_launch(
        self,
        running_by_type: Dict[str, int],
        available_resources: List[dict],
        demands: List[dict],
        gangs: List[List[dict]] | None = None,
    ) -> Tuple[Dict[str, int], List[List[dict]]]:
        """-> ({node_type: count_to_launch}, infeasible_gangs)."""
        gangs = gangs or []
        avail = [dict(a) for a in available_resources]

        # 1) first-fit flat demands onto existing capacity
        unmet: List[dict] = []
        for d in sorted(demands, key=lambda d: -sum(d.values())):
            for a in avail:
                if _fits(a, d):
                    _sub(a, d)
                    break
            else:
                unmet.append(d)

        # 2) gang demands: each gang packs onto ONE node (ICI domain)
        unmet_gangs: List[dict] = []       # gang collapsed to a single bundle
        infeasible: List[List[dict]] = []
        for gang in gangs:
            total: dict = {}
            for b in gang:
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            placed = False
            for a in avail:
                if _fits(a, total):
                    _sub(a, total)
                    placed = True
                    break
            if placed:
                continue
            if any(_fits(dict(t["resources"]), total)
                   for t in self.node_types.values()):
                unmet_gangs.append(total)
            else:
                infeasible.append(gang)

        # 3) pick node types for what's left (min_workers honored first)
        to_launch: Dict[str, int] = {}
        counts = dict(running_by_type)

        def total_workers() -> int:
            return sum(counts.values())

        for name, spec in self.node_types.items():
            need = spec.get("min_workers", 0) - counts.get(name, 0)
            for _ in range(max(0, need)):
                if total_workers() >= self.max_workers:
                    break
                to_launch[name] = to_launch.get(name, 0) + 1
                counts[name] = counts.get(name, 0) + 1
                avail.append(dict(spec["resources"]))

        remaining = sorted(unmet_gangs, key=lambda d: -sum(d.values())) + \
            sorted(unmet, key=lambda d: -sum(d.values()))
        # retry against capacity added by min_workers launches
        still: List[dict] = []
        for d in remaining:
            for a in avail:
                if _fits(a, d):
                    _sub(a, d)
                    break
            else:
                still.append(d)

        while still:
            # utility = demands satisfied per unit of node capacity, so a
            # big TPU host is only chosen over a small CPU node when its
            # extra capacity is actually used (reference's utilization
            # scoring in resource_demand_scheduler._utilization_score)
            best_name, best_key, best_score, best_leftover = \
                None, (-1.0, -1), -1, None
            for name, spec in self.node_types.items():
                if counts.get(name, 0) >= spec.get("max_workers",
                                                   self.max_workers):
                    continue
                if total_workers() >= self.max_workers:
                    break
                cap = dict(spec["resources"])
                capacity = sum(spec["resources"].values()) or 1.0
                score = 0
                leftover = []
                for d in still:
                    if _fits(cap, d):
                        _sub(cap, d)
                        score += 1
                    else:
                        leftover.append(d)
                key = (score / capacity, score)
                if key > best_key:
                    best_name, best_key, best_score, best_leftover = \
                        name, key, score, leftover
            if best_name is None or best_score <= 0:
                # nothing helps (all types maxed or demands unplaceable)
                for d in still:
                    infeasible.append([d])
                break
            to_launch[best_name] = to_launch.get(best_name, 0) + 1
            counts[best_name] = counts.get(best_name, 0) + 1
            still = best_leftover

        return to_launch, infeasible
