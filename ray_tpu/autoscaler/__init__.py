"""Autoscaler: scale a cluster of TPU hosts to match resource demand.

Counterpart of the reference's `python/ray/autoscaler/` — `StandardAutoscaler`
(`_private/autoscaler.py:166`, `update` :368), `LoadMetrics`
(`load_metrics.py`), the bin-packing `resource_demand_scheduler.py`, and the
`NodeProvider` abstraction (`node_provider.py`) with its fake implementation
(`fake_multi_node/node_provider.py:237`). TPU-native difference: node types
describe whole ICI domains (a v5e-8 host, a v4-64 slice) and gang demands
(placement groups with STRICT_PACK) must land on one slice type, so the
packer treats a slice as indivisible for gang bundles.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import FakeNodeProvider, NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import (
    ResourceDemandScheduler,
)

__all__ = [
    "StandardAutoscaler", "LoadMetrics", "NodeProvider", "FakeNodeProvider",
    "ResourceDemandScheduler",
]
