"""DeploymentHandle — the RPC path to a deployment's replicas.

Counterpart of the reference's `serve/handle.py` (RayServeHandle) +
`_private/router.py:875` (Router with power-of-two-choices replica
assignment, `_try_assign_replica` :747). The handle keeps a local view of
the replica set (refreshed from the controller, the reference's long-poll
`LongPollClient` :69) and routes each call to the less-loaded of two
random replicas, tracking in-flight counts client-side.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import ray_tpu
from ray_tpu import exceptions as _exc

from ray_tpu._private.constants import (
    SERVE_HANDLE_REFRESH_S as _REFRESH_PERIOD_S,
)


class _RouterState:
    """Replica view + client-side load tracking, shared by a handle and
    every option-carrying view derived from it (options() must not fork
    the counters, or power-of-two routing runs on partial loads)."""

    def __init__(self):
        self.replicas: List = []
        # actor_id -> list of outstanding ObjectRefs (pruned at pick)
        self.outstanding: dict = {}
        self.last_refresh = 0.0
        self.lock = threading.Lock()
        self.version = -1


class DeploymentHandle:
    # outstanding refs tracked per replica, capped so a caller that never
    # resolves its ObjectRefs can't grow the per-replica list unboundedly
    _MAX_TRACKED = 64

    def __init__(self, deployment_name: str, app_name: str = "default",
                 multiplexed_model_id: str = "", _router=None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._model_id = multiplexed_model_id
        self._router = _router or _RouterState()

    # delegate routing state to the SHARED router object
    @property
    def _replicas(self):
        return self._router.replicas

    @_replicas.setter
    def _replicas(self, v):
        self._router.replicas = v

    @property
    def _outstanding(self):
        return self._router.outstanding

    @_outstanding.setter
    def _outstanding(self, v):
        self._router.outstanding = v

    @property
    def _last_refresh(self):
        return self._router.last_refresh

    @_last_refresh.setter
    def _last_refresh(self, v):
        self._router.last_refresh = v

    @property
    def _lock(self):
        return self._router.lock

    @property
    def _version(self):
        return self._router.version

    @_version.setter
    def _version(self, v):
        self._router.version = v

    # handles must survive pickling into replicas/proxies (composition)
    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._model_id))

    def options(self, *, multiplexed_model_id: str | None = None
                ) -> "DeploymentHandle":
        """Per-call options (reference: handle.options(
        multiplexed_model_id=...) routes to the replica already serving
        that model, serve/multiplex.py). The view SHARES the parent's
        router state (replica cache + load counters)."""
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            _router=self._router)

    def _controller(self):
        from ray_tpu.serve.controller import get_controller
        return get_controller()

    def _refresh(self, force: bool = False) -> None:
        now = time.time()
        if not force and now - self._last_refresh < _REFRESH_PERIOD_S:
            return
        with self._lock:
            if not force and now - self._last_refresh < _REFRESH_PERIOD_S:
                return
            info = ray_tpu.get(
                self._controller().get_replicas.remote(
                    self.deployment_name, self.app_name, self._version),
                timeout=30)
            if info is not None:
                version, replicas = info
                self._version = version
                self._replicas = list(replicas)
                live_ids = {r._actor_id for r in replicas}
                self._outstanding = {
                    aid: refs for aid, refs in self._outstanding.items()
                    if aid in live_ids}
            self._last_refresh = now

    def _load(self, actor_id) -> int:
        """In-flight count for one replica: prune completed refs
        (non-blocking wait) and return how many are still outstanding.
        Pruning filters the live list in place rather than overwriting it,
        so refs appended by a concurrent _record are never dropped."""
        with self._lock:
            refs = list(self._outstanding.get(actor_id, ()))
        if not refs:
            return 0
        try:
            ready, _ = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0, fetch_local=False)
        except Exception:
            ready = []
        done = {r._id for r in ready}
        with self._lock:
            cur = self._outstanding.get(actor_id)
            if cur is None:
                return 0
            cur[:] = [r for r in cur if r._id not in done]
            return len(cur)

    def _record(self, actor_id, ref) -> None:
        """Track an in-flight call so routing sees its load (shared by
        __call__-style and method calls; mutations hold the lock so a
        concurrent _refresh prune can't drop updates)."""
        with self._lock:
            refs = self._outstanding.setdefault(actor_id, [])
            refs.append(ref)
            if len(refs) > self._MAX_TRACKED:
                del refs[:-self._MAX_TRACKED]

    def _pick_replica(self):
        """Power-of-two-choices on client-side in-flight counts
        (reference: router.py _try_assign_replica)."""
        self._refresh()
        replicas = self._replicas
        if not replicas:
            # cold start: block until the deployment has replicas
            deadline = time.time() + 60
            while time.time() < deadline:
                self._refresh(force=True)
                if self._replicas:
                    replicas = self._replicas
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
        if len(replicas) == 1:
            return replicas[0]
        if self._model_id:
            # multiplexing: rendezvous (HRW) hash keeps one model id on
            # one stable replica so its LRU cache keeps hitting, with
            # minimal reshuffle when the replica set changes (reference:
            # model-id-aware routing, serve/multiplex.py)
            import hashlib

            def score(r):
                key = f"{self._model_id}:{r._actor_id}".encode()
                return hashlib.md5(key).digest()
            return max(replicas, key=score)
        a, b = random.sample(replicas, 2)
        return a if self._load(a._actor_id) <= self._load(b._actor_id) else b

    def remote(self, *args, **kwargs):
        """-> ObjectRef of the user callable's result."""
        return self.remote_detailed(*args, **kwargs)[0]

    def remote_detailed(self, *args, **kwargs):
        """-> (ObjectRef, replica_handle). The replica identity lets a
        caller continue a replica-side streaming session (the proxy's
        chunk drain) against the replica that holds the generator."""
        replica = self._pick_replica()
        if self._model_id:
            kwargs = {**kwargs,
                      "__multiplexed_model_id__": self._model_id}
        ref = replica.handle_request.remote(args, kwargs)
        self._record(replica._actor_id, ref)
        return ref, replica

    def stream(self, *args, timeout: Optional[float] = 120.0, **kwargs):
        """Python-side streaming consumption: yields chunks of a
        generator/StreamingResponse deployment result."""
        import ray_tpu
        from ray_tpu.serve.replica import STREAM_MARKER
        ref, replica = self.remote_detailed(*args, **kwargs)
        result = ray_tpu.get(ref, timeout=timeout)
        if not (isinstance(result, dict) and STREAM_MARKER in result):
            yield result
            return
        sid = result[STREAM_MARKER]
        try:
            while True:
                chunks, done = ray_tpu.get(
                    replica.next_chunks.remote(sid), timeout=timeout)
                if chunks is None:
                    raise RuntimeError(
                        f"stream {sid} expired on the replica (idle TTL)")
                yield from chunks
                if done:
                    return
        except GeneratorExit:
            try:
                replica.cancel_stream.remote(sid)
            except Exception:
                pass
            raise

    def call(self, *args, timeout: Optional[float] = 60.0, **kwargs):
        """Synchronous convenience: remote + get."""
        last_err = None
        for _ in range(3):      # retry through replica death (rollouts)
            try:
                return ray_tpu.get(self.remote(*args, **kwargs),
                                   timeout=timeout)
            except (_exc.ActorDiedError, _exc.WorkerCrashedError) as e:
                last_err = e
                self._refresh(force=True)
        raise last_err

    # reference-API sugar: handle.method.remote(...)
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        replica = self._handle._pick_replica()
        if self._handle._model_id:
            kwargs = {**kwargs,
                      "__multiplexed_model_id__": self._handle._model_id}
        ref = replica.handle_method.remote(self._method, args, kwargs)
        self._handle._record(replica._actor_id, ref)
        return ref

    def call(self, *args, timeout: Optional[float] = 60.0, **kwargs):
        return ray_tpu.get(self.remote(*args, **kwargs), timeout=timeout)
