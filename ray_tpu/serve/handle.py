"""DeploymentHandle — the RPC path to a deployment's replicas.

Counterpart of the reference's `serve/handle.py` (RayServeHandle) +
`_private/router.py:875` (Router with power-of-two-choices replica
assignment, `_try_assign_replica` :747). The handle keeps a local view of
the replica set (refreshed from the controller, the reference's long-poll
`LongPollClient` :69) and routes each call to the less-loaded of two
random replicas, tracking in-flight counts client-side.

Fault tolerance (see README "Serve fault tolerance"): `call` retries
replica-death failures with capped exponential backoff + jitter under an
optional cross-attempt deadline budget, and `stream` can fail over
mid-stream — on replica loss it resubmits the prompt plus the
already-emitted tokens to a healthy replica as a fresh prefill
(`token_resume`) and splices the continuation, so a greedy decode stream
completes token-identical to an unkilled run.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import ray_tpu
from ray_tpu import exceptions as _exc

from ray_tpu._private.constants import (
    SERVE_HANDLE_REFRESH_S as _REFRESH_PERIOD_S,
    SERVE_RETRY_BASE_S,
    SERVE_RETRY_CAP_S,
    SERVE_RETRY_MAX_ATTEMPTS,
    SERVE_STREAM_FAILOVERS,
)


class _HandleStats:
    """Process-wide resilience counters for every handle in this process,
    published through the stats->Prometheus bridge as `serve_handle_*`
    series (util/telemetry.py)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._retries = 0
        self._failovers = 0
        from ray_tpu.util import telemetry as _telemetry
        _telemetry.register_stats_source(
            _telemetry.next_name("serve_handle#"), self,
            kind="serve_handle")

    def bump(self, key: str) -> None:
        with self._mu:
            setattr(self, f"_{key}", getattr(self, f"_{key}") + 1)

    def stats(self) -> dict:
        """Resilience counters: ``retries`` is replica-death call
        reattempts, ``failovers`` is mid-stream replica replacements."""
        with self._mu:
            return {"retries": self._retries,
                    "failovers": self._failovers}


HANDLE_STATS = _HandleStats()


def token_resume(args, kwargs, emitted):
    """Default `DeploymentHandle.stream` failover policy for token
    generation: rebuild the submission so a fresh replica prefills
    `prompt + emitted` and decodes only the remainder. Greedy decode over
    replicas with identical weights makes the spliced stream
    token-identical to an unkilled run.

    Returns `(args, kwargs)` for the resubmission, or None when the
    token budget is already exhausted (the stream is simply complete).
    Raises TypeError/ValueError when the stream's chunks are not token
    ids — the caller then re-raises the original replica death, since a
    generic byte stream cannot be replayed safely."""
    if not args:
        raise TypeError("token_resume needs the prompt as args[0]")
    prompt = list(args[0]) + [int(t) for t in emitted]
    if "max_new_tokens" in kwargs:
        remaining = int(kwargs["max_new_tokens"]) - len(emitted)
        if remaining <= 0:
            return None
        return (prompt, *args[1:]), {**kwargs, "max_new_tokens": remaining}
    if len(args) >= 2:
        remaining = int(args[1]) - len(emitted)
        if remaining <= 0:
            return None
        return (prompt, remaining, *args[2:]), kwargs
    return (prompt,), kwargs


class _RouterState:
    """Replica view + client-side load tracking, shared by a handle and
    every option-carrying view derived from it (options() must not fork
    the counters, or power-of-two routing runs on partial loads)."""

    def __init__(self):
        self.replicas: List = []
        # actor_id -> list of outstanding ObjectRefs (pruned at pick)
        self.outstanding: dict = {}
        self.last_refresh = 0.0
        self.lock = threading.Lock()
        # serializes controller round-trips; the router lock above must
        # stay block-free (routing hot path), so the RPC happens here
        self.refresh_lock = threading.Lock()
        self.version = -1


class DeploymentHandle:
    # outstanding refs tracked per replica, capped so a caller that never
    # resolves its ObjectRefs can't grow the per-replica list unboundedly
    _MAX_TRACKED = 64

    def __init__(self, deployment_name: str, app_name: str = "default",
                 multiplexed_model_id: str = "", priority: int | None = None,
                 _router=None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._model_id = multiplexed_model_id
        self._priority = priority
        self._router = _router or _RouterState()

    # delegate routing state to the SHARED router object
    @property
    def _replicas(self):
        return self._router.replicas

    @_replicas.setter
    def _replicas(self, v):
        self._router.replicas = v

    @property
    def _outstanding(self):
        return self._router.outstanding

    @_outstanding.setter
    def _outstanding(self, v):
        self._router.outstanding = v

    @property
    def _last_refresh(self):
        return self._router.last_refresh

    @_last_refresh.setter
    def _last_refresh(self, v):
        self._router.last_refresh = v

    @property
    def _lock(self):
        return self._router.lock

    @property
    def _version(self):
        return self._router.version

    @_version.setter
    def _version(self, v):
        self._router.version = v

    # handles must survive pickling into replicas/proxies (composition)
    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._model_id,
                 self._priority))

    def options(self, *, multiplexed_model_id: str | None = None,
                priority: int | None = None) -> "DeploymentHandle":
        """Per-call options (reference: handle.options(
        multiplexed_model_id=...) routes to the replica already serving
        that model, serve/multiplex.py; `priority=` stamps a priority
        class on every call made through the view — see
        serve/priority.py). The view SHARES the parent's router state
        (replica cache + load counters)."""
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            priority if priority is not None else self._priority,
            _router=self._router)

    def _controller(self):
        from ray_tpu.serve.controller import get_controller
        return get_controller()

    def _refresh(self, force: bool = False) -> None:
        now = time.time()
        if not force and now - self._last_refresh < _REFRESH_PERIOD_S:
            return
        # Controller RPC under the dedicated (blocking-ok) refresh lock;
        # the router lock only brackets the snapshot and the commit, so
        # routing never stalls behind a slow controller round-trip.
        with self._router.refresh_lock:
            with self._lock:
                if not force and \
                        now - self._last_refresh < _REFRESH_PERIOD_S:
                    return
                known = self._version
            info = ray_tpu.get(
                self._controller().get_replicas.remote(
                    self.deployment_name, self.app_name, known),
                timeout=30)
            with self._lock:
                if info is not None:
                    version, replicas = info
                    self._version = version
                    self._replicas = list(replicas)
                    live_ids = {r._actor_id for r in replicas}
                    self._outstanding = {
                        aid: refs
                        for aid, refs in self._outstanding.items()
                        if aid in live_ids}
                self._last_refresh = now

    def _load(self, actor_id) -> int:
        """In-flight count for one replica: prune completed refs
        (non-blocking wait) and return how many are still outstanding.
        Pruning filters the live list in place rather than overwriting it,
        so refs appended by a concurrent _record are never dropped."""
        with self._lock:
            refs = list(self._outstanding.get(actor_id, ()))
        if not refs:
            return 0
        try:
            ready, _ = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0, fetch_local=False)
        except Exception:
            ready = []
        done = {r._id for r in ready}
        with self._lock:
            cur = self._outstanding.get(actor_id)
            if cur is None:
                return 0
            cur[:] = [r for r in cur if r._id not in done]
            return len(cur)

    def _record(self, actor_id, ref) -> None:
        """Track an in-flight call so routing sees its load (shared by
        __call__-style and method calls; mutations hold the lock so a
        concurrent _refresh prune can't drop updates)."""
        with self._lock:
            refs = self._outstanding.setdefault(actor_id, [])
            refs.append(ref)
            if len(refs) > self._MAX_TRACKED:
                del refs[:-self._MAX_TRACKED]

    def _pick_replica(self, exclude: frozenset = frozenset()):
        """Power-of-two-choices on client-side in-flight counts
        (reference: router.py _try_assign_replica). `exclude` drops
        replicas known-dead to this caller (mid-stream failover must not
        resubmit to the corpse before the controller notices it)."""
        self._refresh()
        replicas = [r for r in self._replicas
                    if r._actor_id not in exclude]
        if not replicas:
            # cold start (or every replica excluded): block until the
            # deployment has a usable replica
            deadline = time.time() + 60
            while time.time() < deadline:
                self._refresh(force=True)
                replicas = [r for r in self._replicas
                            if r._actor_id not in exclude]
                if replicas:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas")
        if len(replicas) == 1:
            return replicas[0]
        if self._model_id:
            # multiplexing: rendezvous (HRW) hash keeps one model id on
            # one stable replica so its LRU cache keeps hitting, with
            # minimal reshuffle when the replica set changes (reference:
            # model-id-aware routing, serve/multiplex.py)
            import hashlib

            def score(r):
                key = f"{self._model_id}:{r._actor_id}".encode()
                return hashlib.md5(key).digest()
            return max(replicas, key=score)
        a, b = random.sample(replicas, 2)
        return a if self._load(a._actor_id) <= self._load(b._actor_id) else b

    def remote(self, *args, **kwargs):
        """-> ObjectRef of the user callable's result."""
        return self.remote_detailed(*args, **kwargs)[0]

    def remote_detailed(self, *args, _exclude: frozenset = frozenset(),
                        **kwargs):
        """-> (ObjectRef, replica_handle). The replica identity lets a
        caller continue a replica-side streaming session (the proxy's
        chunk drain) against the replica that holds the generator."""
        replica = self._pick_replica(_exclude)
        if self._model_id:
            kwargs = {**kwargs,
                      "__multiplexed_model_id__": self._model_id}
        if self._priority is not None:
            kwargs = {**kwargs, "__serve_priority__": self._priority}
        from ray_tpu.util import tracing as _tracing
        with _tracing.span("handle.call",
                           {"deployment": self.deployment_name,
                            "app": self.app_name}):
            # the submit inside nests under this span, so the replica's
            # task.execute span attributes the handle hop
            ref = replica.handle_request.remote(args, kwargs)
        self._record(replica._actor_id, ref)
        return ref, replica

    def stream(self, *args, timeout: Optional[float] = 120.0,
               deadline_s: Optional[float] = None,
               failover=token_resume,
               max_failovers: Optional[int] = None, **kwargs):
        """Python-side streaming consumption: yields chunks of a
        generator/StreamingResponse deployment result.

        Resilience: when the serving replica dies mid-stream and a
        `failover` policy is set (default `token_resume`), the handle
        resubmits `failover(args, kwargs, emitted_chunks)` to a healthy
        replica (the dead one excluded) and splices the continuation —
        up to `max_failovers` times. Chunks the policy can't replay
        (non-token streams) re-raise the original death. On ANY abnormal
        exit — abandoned generator, timeout, error — the replica-side
        stream is cancelled so its generator can't leak until the idle
        TTL. `deadline_s` caps total wall time across failovers."""
        from ray_tpu.exceptions import GetTimeoutError
        from ray_tpu.serve.replica import STREAM_MARKER
        if max_failovers is None:
            max_failovers = SERVE_STREAM_FAILOVERS
        deadline = (time.monotonic() + deadline_s) if deadline_s else None

        def left():
            if deadline is None:
                return timeout
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise GetTimeoutError(
                    f"stream deadline of {deadline_s}s exhausted")
            return rem if timeout is None else min(timeout, rem)

        emitted: list = []
        failovers = 0
        cur_args, cur_kwargs = args, kwargs
        exclude: set = set()
        while True:     # one iteration per (re)submission
            ref, replica = self.remote_detailed(
                *cur_args, _exclude=frozenset(exclude), **cur_kwargs)
            sid = None
            finished = False
            try:
                result = ray_tpu.get(ref, timeout=left())
                if not (isinstance(result, dict)
                        and STREAM_MARKER in result):
                    finished = True
                    yield result
                    return
                sid = result[STREAM_MARKER]
                while True:
                    chunks, done = ray_tpu.get(
                        replica.next_chunks.remote(sid), timeout=left())
                    if chunks is None:
                        raise RuntimeError(
                            f"stream {sid} expired on the replica "
                            "(idle TTL)")
                    for c in chunks:
                        emitted.append(c)
                        yield c
                    if done:
                        finished = True
                        return
            except (_exc.ActorDiedError, _exc.WorkerCrashedError) as death:
                sid = None      # replica gone: nothing left to cancel
                if failover is None or failovers >= max_failovers:
                    raise
                try:
                    resume = failover(args, kwargs, tuple(emitted))
                except (TypeError, ValueError):
                    raise death from None    # chunks aren't replayable
                failovers += 1
                HANDLE_STATS.bump("failovers")
                exclude.add(replica._actor_id)
                self._refresh(force=True)
                if resume is None:
                    return      # budget exhausted at death: complete
                cur_args, cur_kwargs = resume
            finally:
                # leak fix: cancel the replica-side stream on ANY
                # abnormal exit (GeneratorExit from an abandoning
                # caller, timeouts, errors) — not just GeneratorExit
                if sid is not None and not finished:
                    try:
                        replica.cancel_stream.remote(sid)
                    except Exception:
                        pass

    def call(self, *args, timeout: Optional[float] = 60.0,
             deadline_s: Optional[float] = None,
             max_retries: Optional[int] = None, **kwargs):
        """Synchronous convenience: remote + get, with bounded retry.

        Only replica-death failures are retried (the result can never
        materialize; resubmission is the only way forward), with capped
        exponential backoff + jitter between attempts. `deadline_s` is a
        total wall-time budget ACROSS attempts — each retry's get
        timeout and backoff shrink to fit what remains."""
        if max_retries is None:
            max_retries = SERVE_RETRY_MAX_ATTEMPTS
        attempts = max(1, max_retries)
        deadline = (time.monotonic() + deadline_s) if deadline_s else None
        last_err = None
        exclude: set = set()
        for attempt in range(attempts):
            t = timeout
            if deadline is not None:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                t = rem if timeout is None else min(timeout, rem)
            replica = None
            try:
                # exclusion: the router snapshot keeps a corpse listed
                # until the controller's next reconcile — a retry must
                # not land on a replica this caller just saw die
                ref, replica = self.remote_detailed(
                    *args, _exclude=frozenset(exclude), **kwargs)
                return ray_tpu.get(ref, timeout=t)
            except (_exc.ActorDiedError, _exc.WorkerCrashedError) as e:
                last_err = e
                if replica is not None:
                    exclude.add(replica._actor_id)
                if attempt + 1 >= attempts:
                    break
                HANDLE_STATS.bump("retries")
                self._refresh(force=True)
                backoff = min(SERVE_RETRY_CAP_S,
                              SERVE_RETRY_BASE_S * (2 ** attempt))
                backoff *= 0.5 + random.random() / 2    # jitter
                if deadline is not None:
                    backoff = min(backoff,
                                  max(0.0, deadline - time.monotonic()))
                time.sleep(backoff)
        if last_err is None:
            from ray_tpu.exceptions import GetTimeoutError
            raise GetTimeoutError(
                f"call() deadline of {deadline_s}s exhausted")
        raise last_err

    # reference-API sugar: handle.method.remote(...)
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        replica = self._handle._pick_replica()
        if self._handle._model_id:
            kwargs = {**kwargs,
                      "__multiplexed_model_id__": self._handle._model_id}
        if self._handle._priority is not None:
            kwargs = {**kwargs,
                      "__serve_priority__": self._handle._priority}
        ref = replica.handle_method.remote(self._method, args, kwargs)
        self._handle._record(replica._actor_id, ref)
        return ref

    def call(self, *args, timeout: Optional[float] = 60.0, **kwargs):
        return ray_tpu.get(self.remote(*args, **kwargs), timeout=timeout)
