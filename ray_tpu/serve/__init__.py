"""ray_tpu.serve — model serving.

Counterpart of the reference's `python/ray/serve/` (SURVEY.md §2.8):
controller-reconciled deployments, replica actors, HTTP ingress,
deployment handles with power-of-two-choices routing, queue-depth
autoscaling, request batching, and `.bind()` model composition.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    run,
    run_disagg,
    set_route,
    shutdown,
    start,
    status,
)
from ray_tpu.exceptions import OverloadedError
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import DeploymentHandle, token_resume
from ray_tpu.serve.http_proxy import Request
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.priority import get_request_priority
from ray_tpu.serve.replica import StreamingResponse
from ray_tpu.serve.schema import apply_config, build_app_from_config

__all__ = [
    "deployment", "Deployment", "Application", "run", "start", "status",
    "shutdown", "delete", "set_route", "get_deployment_handle",
    "DeploymentHandle", "batch", "Request", "StreamingResponse",
    "multiplexed", "get_multiplexed_model_id", "get_request_priority",
    "apply_config",
    "build_app_from_config", "OverloadedError", "token_resume",
    "InferenceEngine", "InferenceReplica",
    "run_disagg", "DisaggHandle", "PrefillReplica", "DecodeReplica",
]

# The inference engine pulls in jax; most serve workers never touch it,
# so it loads lazily (PEP 562) instead of taxing every import.
_LAZY = {"InferenceEngine": "ray_tpu.serve.engine",
         "InferenceReplica": "ray_tpu.serve.engine",
         "DisaggHandle": "ray_tpu.serve.disagg",
         "PrefillReplica": "ray_tpu.serve.disagg",
         "DecodeReplica": "ray_tpu.serve.disagg"}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("serve")
del _rlu
