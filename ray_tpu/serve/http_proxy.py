"""HTTP ingress — async data plane with response streaming.

Counterpart of the reference's `HTTPProxy`
(`serve/_private/http_proxy.py:189`, uvicorn/ASGI + actor wrapper :858,
streaming `replica.py:249`): an aiohttp server runs on a dedicated event
loop inside the proxy actor; request handling never blocks the loop —
replica picks/submits run on a small executor and ObjectRef results are
awaited via futures. Streaming deployments (generators /
StreamingResponse) are transferred replica→proxy in chunk batches and
written through an HTTP chunked response, so a slow client doesn't hold a
replica thread and the first byte leaves before the generator finishes.

Request mapping: the deployment callable receives a `Request` with
method/path/query/headers/body; `json()` parses the body. Responses:
bytes/str passed through; StreamingResponse/generators stream chunked;
any other object is JSON-encoded.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import random
import threading
import time
from dataclasses import dataclass, field

from ray_tpu._private.constants import (
    SERVE_RETRY_BASE_S,
    SERVE_RETRY_CAP_S,
    SERVE_RETRY_MAX_ATTEMPTS,
)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.replica import STREAM_MARKER


@dataclass
class Request:
    method: str
    path: str
    query: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body or b"null")


class HTTPProxy:
    # SLO-admission knobs: how long one controller latency snapshot
    # stays fresh, and how long a non-sheddable request queues at the
    # proxy waiting for the histograms to come back under target.
    _SLO_TTL_S = 0.25
    _SLO_QUEUE_S = 0.5

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from aiohttp import web

        self.host, self.port = host, port
        self._routes: dict = {}           # prefix -> (deployment, app)
        self._handles: dict = {}
        # SLO-aware admission state: cached controller latency snapshot
        # + shed/queue counters (stats() -> Prometheus bridge)
        self._slo_mu = threading.Lock()
        self._slo_cache: dict = {}
        self._slo_fetched = -1e9
        self._slo_sheds = 0
        self._slo_queued = 0
        from ray_tpu.util import telemetry as _telemetry
        self._telemetry_name = _telemetry.register_stats_source(
            _telemetry.next_name("http_proxy#"), self, kind="http_proxy")
        # picks/submits touch blocking plumbing (non-blocking wait() for
        # load probes, socket sends): keep them off the event loop.
        # Streaming drains get their OWN pool — a drain can legitimately
        # block minutes between chunk batches, and sharing one capped pool
        # would let 16 slow streams starve request admission entirely.
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-proxy")
        self._stream_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="serve-stream")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()

        app = web.Application(client_max_size=1 << 28)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._app = app
        self._boot_error: BaseException | None = None

        def run():
            asyncio.set_event_loop(self._loop)
            try:
                runner = web.AppRunner(app, access_log=None)
                self._loop.run_until_complete(runner.setup())
                site = web.TCPSite(runner, host, port)
                self._loop.run_until_complete(site.start())
                for s in site._server.sockets:
                    self.port = s.getsockname()[1]   # resolves port=0
                    break
                self._runner = runner
            except BaseException as e:
                self._boot_error = e
                return
            finally:
                self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-http")
        self._thread.start()
        if not self._started.wait(30):
            raise RuntimeError("HTTP proxy failed to start within 30s")
        if self._boot_error is not None:
            # bind failures must raise at construction (a silently dead
            # proxy reporting the requested port helps nobody)
            raise RuntimeError(
                f"HTTP proxy failed to bind {host}:{port}: "
                f"{self._boot_error}")

    # -- actor control surface (unchanged vs the stdlib proxy) ------------

    def ready(self) -> dict:
        return {"host": self.host, "port": self.port}

    def set_route(self, prefix: str, deployment_name: str,
                  app_name: str) -> bool:
        self._routes[prefix.rstrip("/") or "/"] = (deployment_name, app_name)
        return True

    def get_routes(self) -> dict:
        return dict(self._routes)

    def stop(self) -> bool:
        async def _shutdown():
            await self._runner.cleanup()
            self._loop.stop()
        asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
        return True

    # -- request path -----------------------------------------------------

    def _match(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    async def _handle(self, request):
        from aiohttp import web

        match = self._match(request.path)
        if match is None:
            return web.Response(status=404)
        _, (dep, app_name) = match
        key = (dep, app_name)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(dep, app_name)
        handle = self._handles[key]
        # Priority class rides HTTP as `X-Serve-Priority: <int>` (or a
        # `?priority=<int>` query param; the header wins). The proxy only
        # validates the int here — range checks belong to the engine,
        # which knows its own class count.
        raw_pri = request.headers.get(
            "X-Serve-Priority", request.query.get("priority"))
        priority = 0
        if raw_pri is not None:
            try:
                priority = int(raw_pri)
                handle = handle.options(priority=priority)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "bad_priority",
                     "detail": f"priority must be an int, got {raw_pri!r}"},
                    status=400)
        # SLO-aware admission (disaggregated serving): a request may
        # declare TTFT/TPOT targets; they are checked against the
        # routed deployment's LIVE latency histograms (controller
        # scrape). Unsatisfiable + lowest class -> immediate 429 shed;
        # higher classes queue briefly instead (never SLO-shed).
        raw_ttft = request.headers.get(
            "X-SLO-TTFT-MS", request.query.get("slo_ttft_ms"))
        raw_tpot = request.headers.get(
            "X-SLO-TPOT-MS", request.query.get("slo_tpot_ms"))
        if raw_ttft is not None or raw_tpot is not None:
            reject = await self._slo_admit(dep, app_name, raw_ttft,
                                           raw_tpot, priority)
            if reject is not None:
                return reject
        body = await request.read()
        req = Request(
            method=request.method,
            path=request.path,
            query=dict(request.query),
            headers=dict(request.headers),
            body=body)
        from ray_tpu.util import tracing as _tracing
        root = token = None
        if _tracing.tracing_enabled():
            # Root span of the distributed trace: everything downstream —
            # handle → replica → engine flight recorder — joins this
            # trace_id via TaskSpec stamping + contextvars.
            root, token = _tracing.start_span(
                "http.request",
                {"method": request.method, "path": request.path,
                 "deployment": dep})
        loop = asyncio.get_event_loop()
        from ray_tpu import exceptions as _exc
        attempts = max(1, SERVE_RETRY_MAX_ATTEMPTS)
        try:
            try:
                for attempt in range(attempts):
                    try:
                        ref, replica = await loop.run_in_executor(
                            self._pool, self._call_in_ctx, handle, req,
                            root)
                        result = await self._aget(ref)
                        break
                    except (_exc.ActorDiedError,
                            _exc.WorkerCrashedError):
                        # safely retryable: nothing has been written to
                        # the client yet and a dead replica can never
                        # deliver the result. (Deaths mid-STREAM abort
                        # the chunked response instead — the proxy can't
                        # rewind bytes already on the wire; token-level
                        # failover lives in DeploymentHandle.stream.)
                        if attempt + 1 >= attempts:
                            raise
                        await loop.run_in_executor(
                            self._pool,
                            lambda: handle._refresh(force=True))
                        delay = min(SERVE_RETRY_CAP_S,
                                    SERVE_RETRY_BASE_S * (2 ** attempt))
                        await asyncio.sleep(
                            delay * (0.5 + random.random() / 2))
            except Exception as e:
                if root is not None:
                    root["status"] = "ERROR"
                    root["attributes"]["exception"] = repr(e)
                return self._error_response(e)
            if isinstance(result, dict) and STREAM_MARKER in result:
                return await self._stream_out(request, replica, result)
            if isinstance(result, bytes):
                body, ctype = result, "application/octet-stream"
            elif isinstance(result, str):
                body, ctype = result.encode(), "text/plain"
            else:
                body, ctype = (json.dumps(result).encode(),
                               "application/json")
            return web.Response(status=200, body=body, content_type=ctype)
        finally:
            if root is not None:
                _tracing.end_span(root, token)

    # -- SLO-aware admission ----------------------------------------------

    async def _slo_snapshot(self, dep: str, app_name: str,
                            force: bool = False):
        """This deployment's live latency view, from a briefly-cached
        controller `get_slo_snapshot` RPC (the cache keeps admission off
        the controller's hot path at high request rates). None = no view
        yet (no engine-backed replica has reported), which admits."""
        now = time.monotonic()
        with self._slo_mu:
            if not force and now - self._slo_fetched < self._SLO_TTL_S:
                return self._slo_cache.get(f"{app_name}:{dep}")

        def fetch():
            import ray_tpu
            from ray_tpu.serve.controller import get_controller
            try:
                return ray_tpu.get(
                    get_controller().get_slo_snapshot.remote(), timeout=5)
            except Exception:
                return {}

        loop = asyncio.get_event_loop()
        snaps = await loop.run_in_executor(self._pool, fetch)
        with self._slo_mu:
            self._slo_cache = snaps
            self._slo_fetched = now
        return snaps.get(f"{app_name}:{dep}")

    async def _slo_admit(self, dep: str, app_name: str, raw_ttft,
                         raw_tpot, priority: int):
        """Admission verdict for a request carrying SLO targets: None to
        admit, or the error response to return. A target is
        unsatisfiable when the deployment's live p99 already exceeds it
        — admitting would knowingly blow the SLO and load the pool for
        nothing. Class 0 sheds (429 + Retry-After); higher classes are
        queued up to `_SLO_QUEUE_S` for the histograms to recover, then
        admitted regardless — priority work is delayed, never dropped
        here (the engine's own admission still protects the pool)."""
        from aiohttp import web
        try:
            ttft = float(raw_ttft) if raw_ttft is not None else None
            tpot = float(raw_tpot) if raw_tpot is not None else None
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "bad_slo",
                 "detail": "X-SLO-TTFT-MS / X-SLO-TPOT-MS must be "
                           f"numbers, got {raw_ttft!r}/{raw_tpot!r}"},
                status=400)

        def ok(snap) -> bool:
            return ((ttft is None
                     or ttft >= snap.get("ttft_ms_p99", 0.0))
                    and (tpot is None
                         or tpot >= snap.get("tpot_ms_p99", 0.0)))

        snap = await self._slo_snapshot(dep, app_name)
        if snap is None or ok(snap):
            return None
        if priority <= 0:
            with self._slo_mu:
                self._slo_sheds += 1
            return web.json_response(
                {"error": "slo_shed",
                 "detail": f"deployment {dep!r} p99 "
                           f"ttft={snap.get('ttft_ms_p99', 0.0):.1f}ms/"
                           f"tpot={snap.get('tpot_ms_p99', 0.0):.1f}ms "
                           "exceeds the request's SLO targets"},
                status=429, headers={"Retry-After": "1"})
        with self._slo_mu:
            self._slo_queued += 1
        deadline = time.monotonic() + self._SLO_QUEUE_S
        while time.monotonic() < deadline:
            await asyncio.sleep(self._SLO_TTL_S)
            snap = await self._slo_snapshot(dep, app_name, force=True)
            if snap is None or ok(snap):
                break
        return None

    def stats(self) -> dict:
        """SLO-admission counters, published through the stats bridge as
        ``http_proxy_*`` series: ``slo_sheds`` is requests 429-shed for
        unsatisfiable SLO targets, ``slo_queued`` is requests delayed at
        the proxy instead (non-zero priority class), and ``routes`` is
        the registered route count."""
        with self._slo_mu:
            return {"slo_sheds": self._slo_sheds,
                    "slo_queued": self._slo_queued,
                    "routes": len(self._routes)}

    def _call_in_ctx(self, handle, req, span):
        """Run the handle call on the pool WITH the request's trace
        context: `loop.run_in_executor` does not propagate contextvars,
        so the submit-side TaskSpec stamping would otherwise never see
        the proxy's root span."""
        if span is None:
            return handle.remote_detailed(req)
        from ray_tpu.util import tracing as _tracing
        token = _tracing.attach_context(span)
        try:
            return handle.remote_detailed(req)
        finally:
            _tracing.detach_context(token)

    def _error_response(self, e: BaseException):
        """Typed failure mapping: overload shedding surfaces as 429 with
        a Retry-After hint (clients back off instead of hammering a full
        engine), timeouts as 504; everything else stays 500."""
        from aiohttp import web
        from ray_tpu.exceptions import GetTimeoutError, OverloadedError
        if isinstance(e, OverloadedError):
            return web.json_response(
                {"error": "overloaded", "detail": str(e)},
                status=429, headers={"Retry-After": "1"})
        if isinstance(e, (GetTimeoutError, TimeoutError,
                          asyncio.TimeoutError)):
            return web.Response(status=504, text=str(e))
        return web.Response(status=500, text=str(e))

    async def _stream_out(self, request, replica, marker: dict):
        """Drain a replica-side generator into a chunked HTTP response
        (reference: streaming replica responses, replica.py:249)."""
        from aiohttp import web

        stream_id = marker[STREAM_MARKER]
        resp = web.StreamResponse(
            status=marker.get("status", 200),
            headers={"Content-Type": marker.get(
                "content_type", "application/octet-stream")})
        await resp.prepare(request)
        try:
            while True:
                ref = replica.next_chunks.remote(stream_id)
                chunks, done = await self._aget(ref,
                                                pool=self._stream_pool)
                if chunks is None:
                    # stream expired/unknown on the replica: abort the
                    # connection mid-chunk (a clean EOF would present a
                    # truncated body as a complete response)
                    raise ConnectionError(
                        f"stream {stream_id} expired on the replica")
                for chunk in chunks:
                    await resp.write(_to_bytes(chunk))
                if done:
                    break
            await resp.write_eof()
        except BaseException:
            # client gone / chunk failure: release the replica-side
            # generator rather than leaking it in Replica._streams
            try:
                replica.cancel_stream.remote(stream_id)
            except Exception:
                pass
            raise
        return resp

    async def _aget(self, ref, timeout: float = 300.0, pool=None):
        """Await an ObjectRef on a bounded thread pool — NOT via
        ref.future(), which spawns one OS thread per call."""
        import ray_tpu
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(
            pool or self._pool, lambda: ray_tpu.get(ref, timeout=timeout))


def _to_bytes(chunk) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return json.dumps(chunk).encode()
