"""HTTP ingress.

Counterpart of the reference's `HTTPProxy`
(`serve/_private/http_proxy.py:189`, actor wrapper :858). The reference
rides uvicorn/ASGI; this image has no HTTP framework, so the proxy actor
runs a stdlib ThreadingHTTPServer on a background thread and forwards
requests through DeploymentHandles (the same proxy→replica actor-call
data plane).

Request mapping: the deployment callable receives a `Request` with
method/path/query/headers/body; `json()` parses the body. Responses:
bytes/str passed through; any other object is JSON-encoded.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ray_tpu.serve.handle import DeploymentHandle


@dataclass
class Request:
    method: str
    path: str
    query: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body or b"null")


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host, self.port = host, port
        self._routes: dict = {}           # prefix -> (deployment, app)
        self._handles: dict = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):     # quiet
                pass

            def _dispatch(self):
                try:
                    proxy._serve_one(self)
                except BrokenPipeError:
                    pass
                except Exception as e:     # 500 with the error text
                    try:
                        body = str(e).encode()
                        self.send_response(500)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except Exception:
                        pass

            do_GET = do_POST = do_PUT = do_DELETE = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port     # resolves port=0
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http")
        self._thread.start()

    def ready(self) -> dict:
        return {"host": self.host, "port": self.port}

    def set_route(self, prefix: str, deployment_name: str,
                  app_name: str) -> bool:
        self._routes[prefix.rstrip("/") or "/"] = (deployment_name, app_name)
        return True

    def get_routes(self) -> dict:
        return dict(self._routes)

    def _match(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    def _serve_one(self, handler) -> None:
        parsed = urllib.parse.urlsplit(handler.path)
        match = self._match(parsed.path)
        if match is None:
            handler.send_response(404)
            handler.send_header("Content-Length", "0")
            handler.end_headers()
            return
        _, (dep, app) = match
        key = (dep, app)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(dep, app)
        length = int(handler.headers.get("Content-Length") or 0)
        req = Request(
            method=handler.command,
            path=parsed.path,
            query=dict(urllib.parse.parse_qsl(parsed.query)),
            headers=dict(handler.headers.items()),
            body=handler.rfile.read(length) if length else b"")
        result = self._handles[key].call(req, timeout=120)
        if isinstance(result, bytes):
            body, ctype = result, "application/octet-stream"
        elif isinstance(result, str):
            body, ctype = result.encode(), "text/plain"
        else:
            body, ctype = json.dumps(result).encode(), "application/json"
        handler.send_response(200)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def stop(self) -> bool:
        self._server.shutdown()
        self._server.server_close()
        return True
