"""HTTP ingress — async data plane with response streaming.

Counterpart of the reference's `HTTPProxy`
(`serve/_private/http_proxy.py:189`, uvicorn/ASGI + actor wrapper :858,
streaming `replica.py:249`): an aiohttp server runs on a dedicated event
loop inside the proxy actor; request handling never blocks the loop —
replica picks/submits run on a small executor and ObjectRef results are
awaited via futures. Streaming deployments (generators /
StreamingResponse) are transferred replica→proxy in chunk batches and
written through an HTTP chunked response, so a slow client doesn't hold a
replica thread and the first byte leaves before the generator finishes.

Request mapping: the deployment callable receives a `Request` with
method/path/query/headers/body; `json()` parses the body. Responses:
bytes/str passed through; StreamingResponse/generators stream chunked;
any other object is JSON-encoded.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import random
import threading
from dataclasses import dataclass, field

from ray_tpu._private.constants import (
    SERVE_RETRY_BASE_S,
    SERVE_RETRY_CAP_S,
    SERVE_RETRY_MAX_ATTEMPTS,
)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.replica import STREAM_MARKER


@dataclass
class Request:
    method: str
    path: str
    query: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body or b"null")


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from aiohttp import web

        self.host, self.port = host, port
        self._routes: dict = {}           # prefix -> (deployment, app)
        self._handles: dict = {}
        # picks/submits touch blocking plumbing (non-blocking wait() for
        # load probes, socket sends): keep them off the event loop.
        # Streaming drains get their OWN pool — a drain can legitimately
        # block minutes between chunk batches, and sharing one capped pool
        # would let 16 slow streams starve request admission entirely.
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="serve-proxy")
        self._stream_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="serve-stream")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()

        app = web.Application(client_max_size=1 << 28)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._app = app
        self._boot_error: BaseException | None = None

        def run():
            asyncio.set_event_loop(self._loop)
            try:
                runner = web.AppRunner(app, access_log=None)
                self._loop.run_until_complete(runner.setup())
                site = web.TCPSite(runner, host, port)
                self._loop.run_until_complete(site.start())
                for s in site._server.sockets:
                    self.port = s.getsockname()[1]   # resolves port=0
                    break
                self._runner = runner
            except BaseException as e:
                self._boot_error = e
                return
            finally:
                self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-http")
        self._thread.start()
        if not self._started.wait(30):
            raise RuntimeError("HTTP proxy failed to start within 30s")
        if self._boot_error is not None:
            # bind failures must raise at construction (a silently dead
            # proxy reporting the requested port helps nobody)
            raise RuntimeError(
                f"HTTP proxy failed to bind {host}:{port}: "
                f"{self._boot_error}")

    # -- actor control surface (unchanged vs the stdlib proxy) ------------

    def ready(self) -> dict:
        return {"host": self.host, "port": self.port}

    def set_route(self, prefix: str, deployment_name: str,
                  app_name: str) -> bool:
        self._routes[prefix.rstrip("/") or "/"] = (deployment_name, app_name)
        return True

    def get_routes(self) -> dict:
        return dict(self._routes)

    def stop(self) -> bool:
        async def _shutdown():
            await self._runner.cleanup()
            self._loop.stop()
        asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
        return True

    # -- request path -----------------------------------------------------

    def _match(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    async def _handle(self, request):
        from aiohttp import web

        match = self._match(request.path)
        if match is None:
            return web.Response(status=404)
        _, (dep, app_name) = match
        key = (dep, app_name)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(dep, app_name)
        handle = self._handles[key]
        # Priority class rides HTTP as `X-Serve-Priority: <int>` (or a
        # `?priority=<int>` query param; the header wins). The proxy only
        # validates the int here — range checks belong to the engine,
        # which knows its own class count.
        raw_pri = request.headers.get(
            "X-Serve-Priority", request.query.get("priority"))
        if raw_pri is not None:
            try:
                handle = handle.options(priority=int(raw_pri))
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "bad_priority",
                     "detail": f"priority must be an int, got {raw_pri!r}"},
                    status=400)
        body = await request.read()
        req = Request(
            method=request.method,
            path=request.path,
            query=dict(request.query),
            headers=dict(request.headers),
            body=body)
        from ray_tpu.util import tracing as _tracing
        root = token = None
        if _tracing.tracing_enabled():
            # Root span of the distributed trace: everything downstream —
            # handle → replica → engine flight recorder — joins this
            # trace_id via TaskSpec stamping + contextvars.
            root, token = _tracing.start_span(
                "http.request",
                {"method": request.method, "path": request.path,
                 "deployment": dep})
        loop = asyncio.get_event_loop()
        from ray_tpu import exceptions as _exc
        attempts = max(1, SERVE_RETRY_MAX_ATTEMPTS)
        try:
            try:
                for attempt in range(attempts):
                    try:
                        ref, replica = await loop.run_in_executor(
                            self._pool, self._call_in_ctx, handle, req,
                            root)
                        result = await self._aget(ref)
                        break
                    except (_exc.ActorDiedError,
                            _exc.WorkerCrashedError):
                        # safely retryable: nothing has been written to
                        # the client yet and a dead replica can never
                        # deliver the result. (Deaths mid-STREAM abort
                        # the chunked response instead — the proxy can't
                        # rewind bytes already on the wire; token-level
                        # failover lives in DeploymentHandle.stream.)
                        if attempt + 1 >= attempts:
                            raise
                        await loop.run_in_executor(
                            self._pool,
                            lambda: handle._refresh(force=True))
                        delay = min(SERVE_RETRY_CAP_S,
                                    SERVE_RETRY_BASE_S * (2 ** attempt))
                        await asyncio.sleep(
                            delay * (0.5 + random.random() / 2))
            except Exception as e:
                if root is not None:
                    root["status"] = "ERROR"
                    root["attributes"]["exception"] = repr(e)
                return self._error_response(e)
            if isinstance(result, dict) and STREAM_MARKER in result:
                return await self._stream_out(request, replica, result)
            if isinstance(result, bytes):
                body, ctype = result, "application/octet-stream"
            elif isinstance(result, str):
                body, ctype = result.encode(), "text/plain"
            else:
                body, ctype = (json.dumps(result).encode(),
                               "application/json")
            return web.Response(status=200, body=body, content_type=ctype)
        finally:
            if root is not None:
                _tracing.end_span(root, token)

    def _call_in_ctx(self, handle, req, span):
        """Run the handle call on the pool WITH the request's trace
        context: `loop.run_in_executor` does not propagate contextvars,
        so the submit-side TaskSpec stamping would otherwise never see
        the proxy's root span."""
        if span is None:
            return handle.remote_detailed(req)
        from ray_tpu.util import tracing as _tracing
        token = _tracing.attach_context(span)
        try:
            return handle.remote_detailed(req)
        finally:
            _tracing.detach_context(token)

    def _error_response(self, e: BaseException):
        """Typed failure mapping: overload shedding surfaces as 429 with
        a Retry-After hint (clients back off instead of hammering a full
        engine), timeouts as 504; everything else stays 500."""
        from aiohttp import web
        from ray_tpu.exceptions import GetTimeoutError, OverloadedError
        if isinstance(e, OverloadedError):
            return web.json_response(
                {"error": "overloaded", "detail": str(e)},
                status=429, headers={"Retry-After": "1"})
        if isinstance(e, (GetTimeoutError, TimeoutError,
                          asyncio.TimeoutError)):
            return web.Response(status=504, text=str(e))
        return web.Response(status=500, text=str(e))

    async def _stream_out(self, request, replica, marker: dict):
        """Drain a replica-side generator into a chunked HTTP response
        (reference: streaming replica responses, replica.py:249)."""
        from aiohttp import web

        stream_id = marker[STREAM_MARKER]
        resp = web.StreamResponse(
            status=marker.get("status", 200),
            headers={"Content-Type": marker.get(
                "content_type", "application/octet-stream")})
        await resp.prepare(request)
        try:
            while True:
                ref = replica.next_chunks.remote(stream_id)
                chunks, done = await self._aget(ref,
                                                pool=self._stream_pool)
                if chunks is None:
                    # stream expired/unknown on the replica: abort the
                    # connection mid-chunk (a clean EOF would present a
                    # truncated body as a complete response)
                    raise ConnectionError(
                        f"stream {stream_id} expired on the replica")
                for chunk in chunks:
                    await resp.write(_to_bytes(chunk))
                if done:
                    break
            await resp.write_eof()
        except BaseException:
            # client gone / chunk failure: release the replica-side
            # generator rather than leaking it in Replica._streams
            try:
                replica.cancel_stream.remote(stream_id)
            except Exception:
                pass
            raise
        return resp

    async def _aget(self, ref, timeout: float = 300.0, pool=None):
        """Await an ObjectRef on a bounded thread pool — NOT via
        ref.future(), which spawns one OS thread per call."""
        import ray_tpu
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(
            pool or self._pool, lambda: ray_tpu.get(ref, timeout=timeout))


def _to_bytes(chunk) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return json.dumps(chunk).encode()
