"""Continuous-batching autoregressive inference engine.

The Podracer serving recipe (Hessel et al., 2104.06272): device shapes
are STATIC and the model stays resident. The engine owns a fixed-shape
KV cache of `slots` rows (models.gpt.init_kv_cache); sequences stream
through those slots rather than reshaping the batch per request:

- **prefill** pads each prompt right up to a length *bucket* and writes
  one cache row (`gpt.prefill(slot=...)` — slot and true length are
  traced scalars), so XLA compiles prefill once per bucket, ever.
- **decode** advances ALL slots one token per call through a single
  jitted, cache-donating wrapper around `gpt.decode_step` — compiled
  exactly once for the engine's lifetime (asserted in tests via the
  trace counter). Inactive slots decode garbage at position 0; nobody
  reads it, and the next admission's prefill overwrites the row.
- **continuous batching**: requests are admitted into free slots
  *between* decode steps, so a late arrival never recompiles anything
  and never perturbs resident sequences (decode math is
  row-independent; tests assert exact greedy equality).

Sampling (greedy + temperature) runs inside the jitted functions:
temperature is a per-slot traced vector, the PRNG key is folded with the
step counter, and `temp == 0` rows take the argmax — so switching
sampling modes or admitting a sampled request next to a greedy one is
not a recompile either.

Driving model: `step()` is the one scheduler tick (admit, then decode).
Any number of consumers can call `tokens_for(rid)` concurrently — each
pump acquires the engine lock, ticks the shared engine, and drains its
own per-request queue, which is exactly how `InferenceReplica` streams
concurrent requests through Serve's generator/`next_chunks` machinery.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np


def _default_buckets(max_len: int) -> tuple[int, ...]:
    out, b = [], 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


@dataclass
class _Pending:
    rid: int
    prompt: np.ndarray            # [P] int32
    max_new_tokens: int
    temperature: float
    eos_id: int | None


@dataclass
class _Slot:
    rid: int = -1                 # -1 = free
    token: int = 0                # token the next decode consumes
    pos: int = 0                  # its position in the cache row
    remaining: int = 0
    temperature: float = 0.0
    eos_id: int | None = None

    @property
    def active(self) -> bool:
        return self.rid >= 0


class InferenceEngine:
    """Slot-based continuous-batching scheduler over one GPT model.

    params/cfg are the `models.gpt` pytree and config; `slots` is the
    resident decode batch (the cache's B), `max_len` the per-sequence
    cache capacity (prompt + generated). All device work happens in
    `step()`; `submit()`/`tokens_for()` are the request-side API.
    """

    def __init__(self, params, cfg, *, slots: int = 4,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] | None = None,
                 mesh=None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        from ray_tpu.models import gpt
        self._jax = jax
        self._gpt = gpt
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.num_slots = slots
        self.max_len = cfg.max_seq_len if max_len is None else max_len
        self.buckets = tuple(sorted(
            b for b in (prefill_buckets or _default_buckets(self.max_len))
            if b <= self.max_len))
        if not self.buckets:
            raise ValueError("no prefill bucket <= max_len")
        self.cache = gpt.init_kv_cache(cfg, slots, self.max_len, mesh)
        self._base_key = jax.random.PRNGKey(seed)

        # Compile-once accounting: the counters increment inside the
        # traced python functions, i.e. once per (re)trace. Tests pin
        # decode_traces == 1 across a whole multi-request run.
        self.prefill_traces = 0
        self.decode_traces = 0

        def _sample(logits, temps, key, step):
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            k = jax.random.fold_in(key, step)
            safe = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.random.categorical(
                k, logits.astype(jnp.float32) / safe[:, None]
            ).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        def _prefill(params, tokens, cache, slot, length, temp, key,
                     step):
            self.prefill_traces += 1
            logits, cache = gpt.prefill(
                params, tokens, cache, cfg, mesh,
                lengths=length[None], slot=slot)
            tok = _sample(logits, temp[None], key, step)[0]
            return tok, cache

        def _decode(params, cache, tokens, pos, temps, key, step):
            self.decode_traces += 1
            logits, cache = gpt.decode_step(
                params, tokens, cache, pos, cfg, mesh)
            return _sample(logits, temps, key, step), cache

        # Cache donation: the [L, S, max_len, H, D] k/v buffers are by
        # far the engine's biggest arrays; donating them lets XLA alias
        # input to output so every step updates the cache in place in
        # HBM instead of allocating a second copy.
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(2,))
        self._decode_fn = jax.jit(_decode, donate_argnums=(1,))

        self._slots = [_Slot() for _ in range(slots)]
        self._pending: collections.deque[_Pending] = collections.deque()
        self._rid = 0
        # rid -> deque of emitted token ids; rid dropped when done AND
        # drained (tokens_for pops, then deletes).
        self._out: dict[int, collections.deque] = {}
        self._done: set[int] = set()
        self._lock = threading.RLock()
        self._decode_steps = 0
        self._step_times = collections.deque(maxlen=512)
        self._occupancy = collections.deque(maxlen=512)
        self._prefill_tokens = 0
        self._decode_tokens = 0
        self._prefill_time = 0.0
        self._decode_time = 0.0

    # ------------------------------------------------------------------
    # request side
    # ------------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int = 16,
               temperature: float = 0.0,
               eos_id: int | None = None) -> int:
        """Queue a prompt (sequence of token ids); returns a request id
        for `tokens_for`. Admission happens inside `step()`."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds largest prefill "
                f"bucket {self.buckets[-1]}")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds cache max_len {self.max_len}")
        with self._lock:
            rid = self._rid
            self._rid += 1
            self._out[rid] = collections.deque()
            self._pending.append(_Pending(rid, prompt, max_new_tokens,
                                          temperature, eos_id))
        return rid

    def tokens_for(self, rid: int):
        """Generator of generated token ids for one request. Pumps the
        shared engine: each next() ticks `step()` (under the lock) until
        this request has output, so N concurrent consumers collectively
        drive one continuously-batched device loop."""
        while True:
            tok = None
            with self._lock:   # pop under the lock, yield OUTSIDE it —
                # a generator suspends at yield, and a suspended holder
                # would block every other consumer's pump.
                q = self._out.get(rid)
                if q is None:
                    return
                while not q and rid not in self._done:
                    self.step()
                if q:
                    tok = q.popleft()
                if rid in self._done and not q:
                    self._done.discard(rid)
                    del self._out[rid]
            if tok is None:
                return
            yield tok

    def generate(self, prompt, **kw) -> list[int]:
        """Blocking convenience: submit + drain one request."""
        return list(self.tokens_for(self.submit(prompt, **kw)))

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket for prompt length {n}")

    def _admit(self, slot_idx: int, req: _Pending):
        jnp = self._jax.numpy
        p = req.prompt.size
        bucket = self._bucket_for(p)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :p] = req.prompt
        t0 = time.perf_counter()
        tok, self.cache = self._prefill_fn(
            self.params, jnp.asarray(toks), self.cache,
            np.int32(slot_idx), np.int32(p),
            np.float32(req.temperature), self._base_key,
            np.int32(self._decode_steps))
        tok = int(tok)    # device sync, so the timing is honest
        self._prefill_time += time.perf_counter() - t0
        self._prefill_tokens += p
        s = self._slots[slot_idx]
        s.rid, s.token, s.pos = req.rid, tok, p
        s.remaining = req.max_new_tokens - 1
        s.temperature = req.temperature
        s.eos_id = req.eos_id
        self._emit(s, slot_idx, tok)

    def _emit(self, s: _Slot, slot_idx: int, tok: int):
        """Route one generated token; retire the slot when finished."""
        self._out[s.rid].append(tok)
        hit_eos = s.eos_id is not None and tok == s.eos_id
        # pos of the *next* token; it must still fit in the cache row.
        if s.remaining <= 0 or hit_eos or s.pos + 1 >= self.max_len:
            self._done.add(s.rid)
            self._slots[slot_idx] = _Slot()

    def step(self) -> bool:
        """One scheduler tick: admit pending requests into free slots
        (prefill, which also emits each request's first token), then one
        decode step for every resident sequence. Returns True if any
        device work happened."""
        with self._lock:
            free = [i for i, s in enumerate(self._slots) if not s.active]
            admitted = 0
            while free and self._pending:
                self._admit(free.pop(0), self._pending.popleft())
                admitted += 1
            active = [i for i, s in enumerate(self._slots) if s.active]
            self._occupancy.append(len(active) / self.num_slots)
            if not active:   # idle, or every admission finished at token 1
                return admitted > 0
            jnp = self._jax.numpy
            tokens = np.array([s.token for s in self._slots], np.int32)
            pos = np.array([s.pos for s in self._slots], np.int32)
            temps = np.array([s.temperature for s in self._slots],
                             np.float32)
            t0 = time.perf_counter()
            nxt, self.cache = self._decode_fn(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(pos), jnp.asarray(temps), self._base_key,
                np.int32(self._decode_steps))
            nxt = np.asarray(nxt)    # device sync
            dt = time.perf_counter() - t0
            self._step_times.append(dt)
            self._decode_time += dt
            self._decode_steps += 1
            self._decode_tokens += len(active)
            for i in active:
                s = self._slots[i]
                s.token, s.pos = int(nxt[i]), s.pos + 1
                s.remaining -= 1
                self._emit(s, i, s.token)
            return True

    def run_until_idle(self):
        """Drive the scheduler until every submitted request finished."""
        while True:
            with self._lock:
                busy = self._pending or any(
                    s.active for s in self._slots)
                if not busy:
                    return
                self.step()

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def reset_stats(self):
        """Zero the throughput/latency accounting (NOT the trace
        counters) — benches call this after warmup so compile time stays
        out of the timed region."""
        with self._lock:
            self._decode_steps = 0
            self._prefill_tokens = self._decode_tokens = 0
            self._prefill_time = self._decode_time = 0.0
            self._step_times.clear()
            self._occupancy.clear()

    def stats(self) -> dict:
        with self._lock:
            times = sorted(self._step_times)
            occ = list(self._occupancy)

            def pct(p):
                if not times:
                    return 0.0
                return times[min(len(times) - 1,
                                 int(p / 100 * len(times)))] * 1e3
            return {
                "slots": self.num_slots,
                "active": sum(s.active for s in self._slots),
                "pending": len(self._pending),
                "decode_steps": self._decode_steps,
                "prefill_tokens": self._prefill_tokens,
                "decode_tokens": self._decode_tokens,
                "prefill_time_s": self._prefill_time,
                "decode_time_s": self._decode_time,
                "prefill_traces": self.prefill_traces,
                "decode_traces": self.decode_traces,
                "slot_occupancy": (sum(occ) / len(occ)) if occ else 0.0,
                "p50_token_latency_ms": pct(50),
                "p99_token_latency_ms": pct(99),
            }


class InferenceReplica:
    """Serve deployment hosting one InferenceEngine; `__call__` returns
    a generator of token ids, which `serve.replica` automatically turns
    into a `next_chunks` stream — so `handle.stream(prompt)` yields
    tokens as they are decoded, and concurrent requests continuously
    batch into the shared engine's slots.

    Construction takes *config kwargs*, not arrays: params are
    initialized on the replica from `seed`, so nothing heavyweight rides
    the deployment's pickled init args. Real deployments would load
    checkpointed params here instead.
    """

    def __init__(self, cfg_kwargs: dict | None = None, *,
                 slots: int = 4, max_len: int = 64, seed: int = 0,
                 engine_kwargs: dict | None = None):
        import jax
        from ray_tpu.models import gpt
        cfg = gpt.small(**(cfg_kwargs or {}))
        params = gpt.init_params(jax.random.PRNGKey(seed), cfg)
        self.engine = InferenceEngine(
            params, cfg, slots=slots, max_len=max_len,
            **(engine_kwargs or {}))

    def __call__(self, prompt, max_new_tokens: int = 8,
                 temperature: float = 0.0):
        rid = self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                 temperature=temperature)
        return self.engine.tokens_for(rid)

    def stats(self) -> dict:
        return self.engine.stats()
