"""Continuous-batching autoregressive inference engine over a paged KV
cache.

The Podracer serving recipe (Hessel et al., 2104.06272): device shapes
are STATIC and the model stays resident. The engine owns one fixed block
pool (`models.gpt.init_kv_pool`, ``[L, n_blocks, block_size, H, Dh]``)
and streams ragged traffic through it via int32 block tables — the only
thing that changes between steps is *data*, never shapes:

- **paged allocation**: each request holds exactly the blocks its
  prompt + generation footprint needs (a 100-token chat no longer pins a
  4k-token row). `BlockAllocator` refcounts physical blocks; block 0 is
  the trash block idle decode rows scatter into.
- **radix prefix sharing**: a host-side `RadixTree` maps token prefixes
  to cached blocks at block granularity. A repeated system prompt is
  prefilled ONCE; later requests admit by taking references on the
  shared blocks and prefilling only their suffix. A prefix that ends
  mid-block is shared copy-on-write: the partial block is device-copied
  into a private block before the request writes into it. Zero-ref
  cached prefixes are evicted LRU under pool pressure.
- **chunked prefill**: admission no longer runs a whole prompt's
  prefill synchronously inside `step()`. Prompts prefill in fixed-size
  chunks (bucketized, one compile per chunk bucket) interleaved between
  decode steps — when any sequence is decoding, a tick runs at most ONE
  chunk, so a long admission never stalls in-flight streams for more
  than one chunk's worth of work.
- **decode** advances ALL slots one token per call through a single
  jitted, pool-donating wrapper around `gpt.decode_step_paged` —
  compiled exactly once for the engine's lifetime (asserted in tests
  via the trace counter). Idle and mid-prefill rows decode garbage
  into the trash block; nobody reads it.
- **speculative decoding** (``spec='ngram' | 'draft'``): each tick
  proposes k tokens per slot — n-gram lookahead matches the request's
  recent suffix against its own prompt+output history (zero model
  cost), the draft backend runs a smaller GPT with its own paged pool
  through one jitted k-step scan — then ONE batched verify forward
  (`gpt.verify_step_paged`) scores the whole window and accepts/
  corrects in-jit (greedy exact; temperature via the standard
  rejection-sampling correction, exact for any proposal). Acceptance
  emits up to k+1 tokens per KV-pool read. No device rollback is
  needed on rejection: per-slot `pos` is authoritative, attention
  masks past it, and sequential future writes overwrite stale K/V
  before any read. Decode and verify each still compile exactly once
  (`decode_traces` / `verify_traces`).

- **RL flywheel hooks** (`ray_tpu.rl`): every emitted token is a
  `TokenEvent` — an ``int`` subclass carrying the target model's
  per-token log-probability and the ``params_version`` it was sampled
  under — and `update_params()` hot-swaps new weights into the live
  engine between ticks with NO recompile and NO restart: the new
  pytree (validated leaf-for-leaf against the old one) is copied
  in-place into the old params' donated device buffers, the radix
  prefix cache is flushed (its K/V was computed under the old
  weights), and the version tag bumps so learners can bound staleness
  and apply importance correction. Mid-flight sequences keep decoding
  over their already-written K/V — the standard in-place-sync
  tradeoff (MindSpeed RL, 2507.19017) — which the per-token version
  tags make visible to the learner.

- **priority classes + preemption** (multi-tenant serving): `submit`
  takes a class (``priority=``, 0 = lowest). Admission runs weighted
  shares across backlogged classes (stride scheduling, weight =
  base**class) with an aging escalation bound so low classes never
  starve; overload shedding is class-ordered (the lowest-class QUEUED
  request sheds first, typed `OverloadedError` delivered through its
  `tokens_for`); and when the block pool can't serve a higher class,
  the lowest-class ACTIVE stream is preempted — its written blocks are
  published to the radix tree, its blocks released, and the stream
  requeued as a chunked re-prefill of prompt+emitted with the SAME rid
  and output queue. A resumed greedy stream is token-identical to an
  unpreempted run (same KV ⇒ same continuation — the property the
  serve handle's `token_resume` failover already relies on), including
  across shared-prefix/COW admissions and both spec-decode backends.

Sampling (greedy + temperature) runs inside the jitted functions, as
before. `step()` is the one scheduler tick (admit, chunk, decode);
`submit()` / `tokens_for()` / `cancel()` are the request-side API. A
consumer that stops iterating `tokens_for` releases its request's
blocks and queues automatically (generator finalization cancels it).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ray_tpu.exceptions import OverloadedError
from ray_tpu.util import faults as _faults

logger = logging.getLogger("ray_tpu.serve")


class TokenEvent(int):
    """A generated token id that is also an ``int``, carrying the RL
    metadata the flywheel needs:

    - ``logprob``: the TARGET model's natural (temperature-1)
      log-likelihood of this token given its prefix,
      ``log_softmax(logits)[token]`` in f32 — i.e. log pi(a|s) for the
      learner, regardless of the sampling temperature or whether the
      token came off the plain decode, prefill, or speculative verify
      path. Matches a full-forward recompute to f32 tolerance.
    - ``params_version``: the engine's weight version
      (`InferenceEngine.update_params` bumps it) the token was computed
      under, so learners can bound staleness / importance-correct.

    Subclassing ``int`` keeps every existing consumer working unchanged
    (equality with plain ints, json/pickle, serve streaming)."""

    def __new__(cls, token: int, logprob: float = 0.0,
                params_version: int = 0):
        ev = super().__new__(cls, token)
        ev.logprob = float(logprob)
        ev.params_version = int(params_version)
        return ev

    def __reduce__(self):
        # int subclasses need an explicit recipe for the metadata to
        # survive pickling (object-store / serve transit).
        return (TokenEvent, (int(self), self.logprob,
                             self.params_version))

    def __repr__(self):
        return (f"TokenEvent({int(self)}, logprob={self.logprob:.4f}, "
                f"params_version={self.params_version})")


def _default_buckets(max_len: int) -> tuple[int, ...]:
    out, b = [], 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Refcounted free-list allocator over the physical blocks of a
    paged KV pool. Block 0 is reserved as the engine's trash block
    (never handed out — idle decode rows scatter there), so a pool of
    ``n_blocks`` has ``n_blocks - 1`` usable blocks.

    Invariants (asserted by `check()` and the fuzz tests): a block is
    either free with refcount 0 or allocated with refcount >= 1;
    used + free == n_blocks - 1; decref of a free block (double free)
    raises."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("need at least one usable block")
        self.n_blocks = n_blocks
        self._free = list(range(n_blocks - 1, 0, -1))   # pop() -> 1, 2…
        self._ref = [0] * n_blocks

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("out of KV cache blocks")
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def ref(self, block: int):
        if self._ref[block] <= 0:
            raise RuntimeError(f"ref of free block {block}")
        self._ref[block] += 1

    def decref(self, block: int):
        if block <= 0 or self._ref[block] <= 0:
            raise RuntimeError(f"double free of block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(block)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def check(self):
        assert self.used + self.free == self.n_blocks - 1
        free = set(self._free)
        assert len(free) == len(self._free), "free-list duplicate"
        for b in range(1, self.n_blocks):
            if b in free:
                assert self._ref[b] == 0, f"free block {b} has refs"
            else:
                assert self._ref[b] >= 1, f"used block {b} has no refs"


# ---------------------------------------------------------------------------
# radix tree over token prefixes
# ---------------------------------------------------------------------------

def _common(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class _RadixNode:
    __slots__ = ("key", "blocks", "children", "parent", "last_access")

    def __init__(self, key, blocks, parent):
        self.key = key              # tuple of tokens, len % bs == 0
        self.blocks = blocks        # physical block per key block
        self.children = {}          # first-block token tuple -> node
        self.parent = parent
        self.last_access = 0


class RadixTree:
    """Host-side radix tree mapping token prefixes to cached KV blocks.

    Keys are block-aligned (every edge covers whole blocks of
    ``block_size`` tokens); edges are path-compressed and split at block
    boundaries when sequences diverge inside them. Tree blocks are
    IMMUTABLE — only full prompt blocks are ever inserted, and decode
    never writes into a full block — so sharing needs no
    synchronization. A match may end mid-block; the caller then shares
    that block read-only and must copy-on-write before writing
    (`InferenceEngine._try_admit`).

    The tree holds one allocator reference per block it records;
    `evict()` walks zero-ref leaves (blocks only the tree still holds)
    in LRU order and releases them."""

    def __init__(self, block_size: int, allocator: BlockAllocator):
        self.bs = block_size
        self.alloc = allocator
        self.root = _RadixNode((), [], None)
        self._clock = 0

    # -- internals ----------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _best_child(self, node, rest):
        best, best_c = None, 0
        for child in node.children.values():
            c = _common(child.key, rest)
            if c > best_c:
                best, best_c = child, c
        return best, best_c

    def _split(self, node, fb: int):
        """Split `node`'s edge after `fb` blocks; returns the new upper
        node (which keeps the prefix blocks)."""
        parent = node.parent
        cut = fb * self.bs
        upper = _RadixNode(node.key[:cut], node.blocks[:fb], parent)
        upper.last_access = node.last_access
        del parent.children[node.key[:self.bs]]
        parent.children[upper.key[:self.bs]] = upper
        node.key = node.key[cut:]
        node.blocks = node.blocks[fb:]
        node.parent = upper
        upper.children[node.key[:self.bs]] = node
        return upper

    def _nodes(self):
        stack = [self.root]
        while stack:
            nd = stack.pop()
            yield nd
            stack.extend(nd.children.values())

    # -- public -------------------------------------------------------

    def match(self, tokens):
        """Longest cached prefix of `tokens`: returns
        ``(blocks, matched)`` where `blocks` covers
        ``ceil(matched / bs)`` physical blocks. When ``matched % bs``
        is nonzero the last block is only partially matched — the
        caller shares it read-only and must COW before writing."""
        toks = tuple(int(t) for t in tokens)
        node, blocks, matched = self.root, [], 0
        now = self._tick()
        while matched < len(toks):
            rest = toks[matched:]
            best, c = self._best_child(node, rest)
            if best is None or c == 0:
                break
            best.last_access = now
            if c == len(best.key) and c < len(rest):
                blocks += best.blocks
                matched += c
                node = best
                continue
            fb = c // self.bs
            blocks += best.blocks[:fb]
            if c % self.bs:
                blocks.append(best.blocks[fb])
            matched += c
            break
        return blocks, matched

    def insert(self, tokens, blocks):
        """Record `tokens` (truncated down to a block multiple) as a
        cached prefix backed by `blocks` (one physical id per logical
        block of `tokens`). Existing matches are walked (and split at a
        block boundary on divergence); only the unmatched tail is
        adopted, taking one tree reference per newly-held block."""
        n = (len(tokens) // self.bs) * self.bs
        toks = tuple(int(t) for t in tokens[:n])
        node, i = self.root, 0
        now = self._tick()
        while i < n:
            rest = toks[i:]
            best, c = self._best_child(node, rest)
            fb = c // self.bs if best is not None else 0
            if fb == 0:
                blks = list(blocks[i // self.bs: n // self.bs])
                child = _RadixNode(rest, blks, node)
                child.last_access = now
                node.children[rest[:self.bs]] = child
                for b in blks:
                    self.alloc.ref(b)
                return
            best.last_access = now
            if fb * self.bs < len(best.key):
                best = self._split(best, fb)
                best.last_access = now
            node = best
            i += fb * self.bs

    def evict(self, need: int) -> int:
        """Free zero-ref cached prefixes (blocks only the tree holds),
        LRU leaves first, until `need` blocks have been released or
        nothing more is evictable. Returns blocks freed."""
        freed = 0
        while freed < need:
            leaves = [nd for nd in self._nodes()
                      if nd is not self.root and not nd.children
                      and all(self.alloc.refcount(b) == 1
                              for b in nd.blocks)]
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: nd.last_access)
            for b in victim.blocks:
                self.alloc.decref(b)
            freed += len(victim.blocks)
            del victim.parent.children[victim.key[:self.bs]]
        return freed

    def clear(self) -> int:
        """Drop every cached prefix (used by tests); returns blocks
        freed. Nodes whose blocks live requests still reference are
        kept."""
        return self.evict(self.n_blocks() or 1)

    def flush(self) -> int:
        """Drop the WHOLE tree unconditionally — every node, including
        ones whose blocks live requests still reference (the tree's own
        reference is released; the requests keep theirs, so their blocks
        stay alive until the slot retires). Used on weight hot-swap:
        cached prefix K/V was computed under the old params and must not
        be shared into post-swap admissions. Returns blocks whose LAST
        reference was the tree's (i.e. blocks actually freed)."""
        freed = 0
        for nd in self._nodes():
            if nd is self.root:
                continue
            for b in nd.blocks:
                self.alloc.decref(b)
                if self.alloc.refcount(b) == 0:
                    freed += 1
        self.root = _RadixNode((), [], None)
        return freed

    def n_blocks(self) -> int:
        return sum(len(nd.blocks) for nd in self._nodes())

    def n_nodes(self) -> int:
        return sum(1 for nd in self._nodes()) - 1   # minus root


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

@dataclass
class _Pending:
    rid: int
    prompt: np.ndarray            # [P] int32
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    ts: float = 0.0               # submit time (queue-wait accounting)
    priority: int = 0             # class (0 = lowest); admission order,
    # shed order, and preemption eligibility all key off it
    resumed: bool = False         # a preempted stream's re-prefill:
    # prompt is the ORIGINAL prompt + every token already delivered, so
    # admission must not re-count TTFT/queue-wait for it
    aged: bool = False            # escalated past the weighted-share
    # order by the aging bound (counted once per request)


@dataclass
class _Slot:
    rid: int = -1
    phase: str = "idle"           # idle | prefill | decode
    prompt: np.ndarray | None = None
    filled: int = 0               # prompt tokens whose KV is resident
    blocks: list = field(default_factory=list)
    table: np.ndarray | None = None   # [max_blocks] int32 (0 = trash)
    order: int = 0                # admission sequence (chunk FIFO)
    token: int = 0                # token the next decode consumes
    token_logp: float = 0.0       # its logprob (parked through prefill)
    token_ver: int = 0            # params_version it was computed under
    pos: int = 0                  # its position in the logical sequence
    version: int = 0              # params_version at admission (a slot
    # admitted under old weights must not publish its prefix blocks to
    # the radix tree after a swap — its K/V would be stale)
    remaining: int = 0
    temperature: float = 0.0
    eos_id: int | None = None
    submit_ts: float = 0.0
    priority: int = 0
    resumed: bool = False
    # every token this stream has emitted, in order — preemption
    # requeues prompt+emitted as a re-prefill, which (greedy) resumes
    # token-identical: the KV it recomputes is exactly the KV released
    emitted: list = field(default_factory=list)
    # speculative decoding state: the request's token history (prompt +
    # emitted, n-gram lookahead's corpus) and, for the draft-model
    # backend, this slot's blocks/table in the DRAFT pool.
    history: list = field(default_factory=list)
    draft_blocks: list = field(default_factory=list)
    draft_table: np.ndarray | None = None
    draft_filled: int = 0

    @property
    def active(self) -> bool:
        return self.phase != "idle"


class InferenceEngine:
    """Slot-based continuous-batching scheduler over one GPT model with
    a paged, prefix-shared KV cache.

    params/cfg are the `models.gpt` pytree and config; `slots` is the
    resident decode batch, `max_len` the per-sequence logical capacity
    (prompt + generated). `block_size` sets the paging granule and
    `cache_blocks` the pool's usable block count (default: enough for
    every slot at full length — shrink it to trade HBM for prefix-cache
    churn). `prefill_chunk` caps prompt tokens absorbed per scheduler
    tick while anything is decoding; `prefix_cache=False` disables the
    radix tree. All device work happens in `step()`."""

    def __init__(self, params, cfg, *, slots: int = 4,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] | None = None,
                 block_size: int = 16,
                 cache_blocks: int | None = None,
                 prefill_chunk: int | None = None,
                 prefix_cache: bool = True,
                 spec: str | None = None, spec_k: int = 4,
                 ngram_max: int = 3, ngram_min: int = 1,
                 draft_params=None, draft_cfg=None,
                 draft_cache_blocks: int | None = None,
                 mesh=None, seed: int = 0,
                 telemetry_sample: float | None = None,
                 max_queue: int | None = None,
                 shed_high_water: float | None = None,
                 watchdog_s: float | None = None,
                 priority_classes: int | None = None,
                 priority_aging_s: float | None = None,
                 priority_weight_base: float | None = None,
                 role: str = "colocated"):
        import jax
        import jax.numpy as jnp
        from ray_tpu.models import gpt
        self._jax = jax
        self._gpt = gpt
        self.cfg = cfg
        # Disaggregated serving role. "prefill": this engine runs
        # chunked prefill only — a completed prompt's KV blocks are
        # gathered to host and parked as a handoff blob for a decode
        # engine to import; nothing ever enters the decode phase here.
        # "decode": behaviorally a colocated engine (it can still serve
        # whole requests) that additionally advertises itself as an
        # import target — the role tag drives serve routing, per-role
        # autoscaling signals, and per-role telemetry. "colocated"
        # (default): the classic single-engine path. import_handoff is
        # available on any non-prefill engine.
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        self.role = role
        self.params = params
        self.mesh = mesh
        self.num_slots = slots
        self.max_len = cfg.max_seq_len if max_len is None else max_len
        self.block_size = block_size
        self.max_blocks = -(-self.max_len // block_size)
        self.cache_blocks = (slots * self.max_blocks
                             if cache_blocks is None else cache_blocks)
        self.buckets = tuple(sorted(
            b for b in (prefill_buckets or _default_buckets(self.max_len))
            if b <= self.max_len))
        if not self.buckets:
            raise ValueError("no prefill bucket <= max_len")
        self.prefill_chunk = (min(64, self.buckets[-1])
                              if prefill_chunk is None else prefill_chunk)
        # Chunk capacities: the existing buckets up to the budget, plus
        # the budget itself — one prefill compile per capacity, ever.
        self.chunk_buckets = tuple(sorted(
            {b for b in self.buckets if b < self.prefill_chunk}
            | {self.prefill_chunk}))
        # +1: physical block 0 is the trash block (idle rows write there).
        self.cache = gpt.init_kv_pool(cfg, self.cache_blocks + 1,
                                      block_size, mesh)
        self._alloc = BlockAllocator(self.cache_blocks + 1)
        self._tree = (RadixTree(block_size, self._alloc)
                      if prefix_cache else None)
        self._base_key = jax.random.PRNGKey(seed)

        # --- speculative decoding setup -------------------------------
        if spec not in (None, "ngram", "draft"):
            raise ValueError(f"unknown spec backend {spec!r}")
        if spec is not None and spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        self.spec = spec
        self.spec_k = int(spec_k)
        # Verify window: [current token, k speculated tokens].
        self.spec_window = self.spec_k + 1
        self.ngram_max, self.ngram_min = int(ngram_max), int(ngram_min)
        self.draft_cfg, self.draft_params = draft_cfg, draft_params
        if spec == "draft":
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "spec='draft' needs draft_params and draft_cfg")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft model must share the tokenizer")
            self.draft_cache_blocks = (
                self.cache_blocks if draft_cache_blocks is None
                else draft_cache_blocks)
            self.draft_cache = gpt.init_kv_pool(
                draft_cfg, self.draft_cache_blocks + 1, block_size, mesh)
            self._draft_alloc = BlockAllocator(self.draft_cache_blocks + 1)
        else:
            self.draft_cache_blocks = 0
            self.draft_cache = None
            self._draft_alloc = None
        if mesh is not None:
            from ray_tpu.parallel.sharding import engine_io_shardings
            self._io_sh = engine_io_shardings(mesh)
        else:
            self._io_sh = None

        # Compile-once accounting: the counters increment inside the
        # traced python functions, i.e. once per (re)trace. Tests pin
        # decode_traces == 1 (and verify_traces == 1 under speculation)
        # across a whole multi-request run.
        self.prefill_traces = 0
        self.decode_traces = 0
        self.verify_traces = 0
        self.draft_traces = 0
        self.draft_prefill_traces = 0
        self.quantize_traces = 0

        # --- int8 weight-only path (cfg.weight_dtype="int8") ----------
        # Quantize once at construction; update_params re-runs the same
        # jitted fn on every swap, so trainers keep publishing f32
        # masters and quantization rides the swap (zero decode/verify
        # retraces — the tree the compiled paths close over keeps its
        # shapes and dtypes). One trace per distinct tree shape: target
        # and draft each at most once, ever.
        def _quantize(p):
            self.quantize_traces += 1
            return gpt.quantize_params(p)

        self._quant_target = cfg.weight_dtype == "int8"
        self._quant_draft = (spec == "draft"
                             and draft_cfg.weight_dtype == "int8")
        self._quantize_fn = (jax.jit(_quantize)
                             if self._quant_target or self._quant_draft
                             else None)
        if self._quant_target:
            self.params = self._quantize_fn(self.params)
        if self._quant_draft:
            self.draft_params = self._quantize_fn(self.draft_params)

        # Capacity gauges: total device bytes of the block pool(s) and
        # the bytes one cached position costs — the lever kv_dtype
        # pulls (stats()/bench_infer surface both).
        self._pool_bytes = sum(
            int(arr.nbytes) for arr in self.cache.values())
        if self.draft_cache is not None:
            self._pool_bytes += sum(
                int(arr.nbytes) for arr in self.draft_cache.values())
        self._kv_bytes_per_token = (
            sum(int(arr.nbytes) for arr in self.cache.values())
            / ((self.cache_blocks + 1) * block_size))

        def _sample(logits, temps, key, step):
            """Sample one token per row; also return the model's NATURAL
            (temperature-1) f32 log-likelihood of the sampled token —
            the per-token logprob the RL flywheel trains against."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            k = jax.random.fold_in(key, step)
            safe = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.random.categorical(
                k, logits.astype(jnp.float32) / safe[:, None]
            ).astype(jnp.int32)
            tok = jnp.where(temps > 0, sampled, greedy)
            nat = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = jnp.take_along_axis(nat, tok[:, None], axis=-1)[:, 0]
            return tok, logp

        def _prefill(params, tokens, cache, table, start, length, temp,
                     key, step):
            self.prefill_traces += 1
            logits, cache = gpt.prefill_paged(
                params, tokens, cache, cfg, mesh, block_table=table,
                start=start, length=length)
            tok, logp = _sample(logits, temp[None], key, step)
            return tok[0], logp[0], cache

        def _decode(params, cache, tokens, pos, tables, temps, key,
                    step):
            self.decode_traces += 1
            logits, cache = gpt.decode_step_paged(
                params, tokens, cache, pos, tables, cfg, mesh)
            tok, logp = _sample(logits, temps, key, step)
            return tok, logp, cache

        def _verify(params, cache, tokens, pos, tables, temps, key,
                    step):
            """One batched W-token forward + in-jit accept/correct.

            `tokens[:, 0]` is each slot's current token, `tokens[:, 1:]`
            its k speculated continuations. Returns ``(out [B, W],
            accepted [B], cache)`` where `out[:, :accepted + 1]` are the
            tokens to emit: the accepted drafts followed by one bonus
            (all accepted) or corrected (first rejection) target token.
            Rejected positions need NO device rollback — `pos` is
            authoritative, attention masks past it, and sequential
            future writes overwrite the stale K/V before any read.
            """
            self.verify_traces += 1
            logits, cache = gpt.verify_step_paged(
                params, tokens, cache, pos, tables, cfg, mesh)
            b, w = tokens.shape
            drafts = tokens[:, 1:]                       # [B, W-1]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            k = jax.random.fold_in(key, step)
            safe = jnp.where(temps > 0, temps, 1.0)
            logp = jax.nn.log_softmax(
                logits / safe[:, None, None], axis=-1)   # [B, W, V]
            # Accept draft j iff it matches greedy (temp 0) or w.p.
            # p_target(draft) (rejection sampling with the draft as a
            # point-mass proposal — exact for ANY proposal, so padded /
            # garbage drafts stay distribution-correct).
            p_draft = jnp.exp(jnp.take_along_axis(
                logp[:, :-1], drafts[..., None], axis=-1)[..., 0])
            u = jax.random.uniform(jax.random.fold_in(k, 1),
                                   drafts.shape)
            match = jnp.where((temps > 0)[:, None], u < p_draft,
                              drafts == greedy[:, :-1])
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
            accepted = jnp.sum(acc, axis=1)              # [B] in [0,W-1]
            # Residual for the first rejected position: target dist with
            # the rejected draft masked out. Col W-1 (the bonus token
            # when everything is accepted) is sampled unmasked.
            res = logp.at[jnp.arange(b)[:, None],
                          jnp.arange(w - 1)[None, :], drafts].set(-1e30)
            corr = jax.random.categorical(
                jax.random.fold_in(k, 2), res, axis=-1).astype(jnp.int32)
            corr = jnp.where((temps > 0)[:, None], corr, greedy)
            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros_like(drafts[:, :1])], axis=1)
            cols = jnp.arange(w)[None, :]
            out = jnp.where(cols < accepted[:, None], drafts_pad, corr)
            # Natural (temperature-1) logprob of each emitted token:
            # logits[:, j] is the next-token distribution after the
            # prefix extended by out[:, :j], so column j's emitted token
            # scores against column j's untempered log-softmax — same
            # contract as the plain decode path.
            nat = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            out_lp = jnp.take_along_axis(
                nat, out[..., None], axis=-1)[..., 0]
            return out, out_lp, accepted, cache

        # Cache donation: the [L, n_blocks, bs, H, D] pool is by far the
        # engine's biggest array; donating it lets XLA alias input to
        # output so every step updates the pool in place in HBM.
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(2,))
        self._decode_fn = jax.jit(_decode, donate_argnums=(1,))
        self._copy_fn = jax.jit(gpt.copy_block, donate_argnums=(0,))
        self._verify_fn = (jax.jit(_verify, donate_argnums=(1,))
                           if spec is not None else None)

        # Disaggregation transport jits: gather one block's KV (payload
        # plus any int8 scale rows) into standalone device arrays for
        # host export, and scatter one transferred block back into a
        # pool. The block index is traced, so each compiles once per
        # pool geometry — target and draft pools differ in shape, hence
        # at most two traces each (sentinel-capped below).
        self.kv_gather_traces = 0
        self.kv_scatter_traces = 0

        def _gather(cache, idx):
            self.kv_gather_traces += 1
            return gpt.gather_block(cache, idx)

        def _scatter_blk(cache, block, idx):
            self.kv_scatter_traces += 1
            return gpt.scatter_block(cache, block, idx)

        self._gather_fn = jax.jit(_gather)
        self._scatter_block_fn = jax.jit(_scatter_blk,
                                         donate_argnums=(0,))

        if spec == "draft":
            W = self.spec_window

            def _propose(dparams, dcache, tokens, pos, tables, temps,
                         key, step):
                """W draft decode steps as one jitted scan: consume
                c_0..c_{W-1}, write their K/V at pos..pos+W-1, sample
                c_1..c_W; the first W-1 samples are the proposal (the
                last scan step exists only to write d_{k}'s K/V so the
                draft cache stays lockstep with the target's)."""
                self.draft_traces += 1
                k = jax.random.fold_in(jax.random.fold_in(key, step), 3)

                def body(carry, i):
                    tok, cache = carry
                    logits, cache = gpt.decode_step_paged(
                        dparams, tok, cache, pos + i, tables,
                        draft_cfg, mesh)
                    nxt, _ = _sample(logits, temps, k, i)
                    return (nxt, cache), nxt

                (_, dcache), outs = jax.lax.scan(
                    body, (tokens, dcache),
                    jnp.arange(W, dtype=jnp.int32))
                return outs[:-1].T, dcache               # [B, W-1]

            def _draft_prefill(dparams, tokens, dcache, table, start,
                               length):
                self.draft_prefill_traces += 1
                _, dcache = gpt.prefill_paged(
                    dparams, tokens, dcache, draft_cfg, mesh,
                    block_table=table, start=start, length=length)
                return dcache

            self._propose_fn = jax.jit(_propose, donate_argnums=(1,))
            self._draft_prefill_fn = jax.jit(_draft_prefill,
                                             donate_argnums=(2,))
        else:
            self._propose_fn = None
            self._draft_prefill_fn = None

        self._slots = [_Slot() for _ in range(slots)]
        self._pending: collections.deque[_Pending] = collections.deque()
        self._rid = 0
        self._admit_seq = 0
        # rid -> deque of emitted token ids; rid dropped when done AND
        # drained (tokens_for pops, then deletes) or cancelled.
        self._out: dict[int, collections.deque] = {}
        self._done: set[int] = set()
        # rid -> exception for requests terminated while QUEUED (class-
        # ordered shedding): tokens_for raises it to the consumer.
        self._errors: dict[int, Exception] = {}
        self._lock = threading.RLock()

        # --- disaggregated prefill/decode handoff state ---------------
        # Export side (role="prefill"): rid -> host-side KV blob parked
        # when the prompt's prefill completes, until the serve layer (or
        # a test) collects it via handoff_for/take_handoff. Import side
        # (any non-prefill role): FIFO of (rid, blob) waiting for a free
        # slot; `_import_rids` mirrors it for O(1) membership.
        self._handoffs: dict[int, dict] = {}
        self._imports: collections.deque = collections.deque()
        self._import_rids: set[int] = set()
        self._handoffs_exported = 0
        self._imports_completed = 0
        self._handoffs_abandoned = 0
        self._kv_blocks_exported = 0
        self._kv_blocks_imported = 0
        self._kv_export_bytes = 0
        self._kv_import_bytes = 0
        self._kv_export_ms = collections.deque(maxlen=256)
        self._kv_import_ms = collections.deque(maxlen=256)

        # --- priority classes (multi-tenant admission) ----------------
        from ray_tpu._private.constants import (
            ENGINE_PRIORITY_AGING_S, ENGINE_PRIORITY_CLASSES,
            ENGINE_PRIORITY_WEIGHT_BASE)
        self.priority_classes = (ENGINE_PRIORITY_CLASSES
                                 if priority_classes is None
                                 else int(priority_classes))
        if self.priority_classes < 1:
            raise ValueError("priority_classes must be >= 1")
        self.priority_aging_s = (ENGINE_PRIORITY_AGING_S
                                 if priority_aging_s is None
                                 else float(priority_aging_s))
        if self.priority_aging_s <= 0:
            raise ValueError("priority_aging_s must be > 0")
        self.priority_weight_base = (ENGINE_PRIORITY_WEIGHT_BASE
                                     if priority_weight_base is None
                                     else float(priority_weight_base))
        if self.priority_weight_base < 1.0:
            raise ValueError("priority_weight_base must be >= 1")
        # stride-scheduler pass value per backlogged class; shares the
        # scheduler lock (the admission queue has no lock of its own —
        # R004: no new lock-order edge)
        self._class_pass: dict[int, float] = {}
        # per-class counters/waits (lazily created per class seen)
        self._per_class: dict[int, dict] = {}
        self._class_waits: dict[int, collections.deque] = {}
        self._preemptions = 0
        self._reprefill_blocks = 0
        self._aging_promotions = 0
        # Serializes weight hot-swaps; exists so the blocking
        # host->device upload in _place_tree happens OUTSIDE _lock.
        self._swap_mutex = threading.Lock()
        self._decode_steps = 0
        self._step_times = collections.deque(maxlen=512)
        self._occupancy = collections.deque(maxlen=512)
        self._block_util = collections.deque(maxlen=512)
        self._prefill_tokens = 0
        self._decode_tokens = 0
        self._prefill_time = 0.0
        self._decode_time = 0.0
        self._prefill_chunks = 0
        self._prefix_hit_tokens = 0
        self._prompt_tokens = 0
        self._cow_copies = 0
        self._evicted_blocks = 0
        self._cancelled = 0
        self._max_admission_stall = 0.0
        # Windowed / speculative accounting (all reset_stats-covered).
        self._tok_window = collections.deque(maxlen=512)  # (dt, tokens)
        self._queue_waits = collections.deque(maxlen=512)  # submit->tok1
        self._decode_slot_steps = 0   # sum of decoding-slot count/step
        self._spec_steps = 0
        self._spec_proposed = 0
        self._spec_accepted = 0

        # --- graceful degradation: admission shedding + tick watchdog.
        # Both OFF by default: an engine with no bounds queues exactly as
        # before (the autoscaler's queue_depth signal depends on queues
        # being allowed to form). Opt in per deployment via
        # engine_kwargs.
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if shed_high_water is not None and not 0.0 < shed_high_water <= 1.0:
            raise ValueError("shed_high_water must be in (0, 1]")
        self.max_queue = max_queue
        self.shed_high_water = shed_high_water
        self._sheds = 0
        self._watchdog_s = watchdog_s
        self._watchdog_stalls = 0
        self._tick_seq = 0
        self._tick_started: float | None = None
        self._watchdog_stop = threading.Event()
        if watchdog_s is not None:
            if watchdog_s <= 0:
                raise ValueError("watchdog_s must be > 0")
            t = threading.Thread(target=self._watchdog_loop, daemon=True,
                                 name="engine-watchdog")
            t.start()

        # --- RL flywheel: in-place donated weight hot-swap ------------
        # update_params() copies a new pytree INTO the old params'
        # device buffers (donation lets XLA alias input->output leaf by
        # leaf), so the arrays the jitted decode/verify closures see
        # keep their shapes, dtypes, shardings — and, critically, their
        # identity as far as compiled executables are concerned: no
        # retrace, no recompile, no restart. The source pytree is NOT
        # donated — the trainer keeps its own state alive.
        self._params_version = 0
        self._swaps = 0
        self._swap_pending_ts: float | None = None
        self._last_swap_ms = 0.0
        self.swap_traces = 0   # traces once per distinct treedef
                               # (target and draft trees each once)

        def _swap(old, new):
            self.swap_traces += 1
            return jax.tree.map(jnp.copy, new)

        self._swap_fn = jax.jit(_swap, donate_argnums=(0,))

        # --- flight recorder + retrace sentinel (util.telemetry) ------
        # Per-request lifecycle tracing (sampled; telemetry_sample
        # overrides RAY_TPU_TELEMETRY_SAMPLE) and the runtime watcher
        # that enforces the compile-once contract the tests above pin.
        # Shape-pinned paths carry hard caps from construction; the
        # bucket-dependent prefill paths join on arm_retrace_sentinel().
        from ray_tpu.util import telemetry as _telemetry
        self.name = _telemetry.next_name("engine")
        self._recorder = _telemetry.FlightRecorder(
            self.name, sample=telemetry_sample)
        self._sentinel = _telemetry.RetraceSentinel(self.name)
        self._sentinel.watch("decode", lambda: self.decode_traces, cap=1,
                             registered=True)
        self._sentinel.watch("swap", lambda: self.swap_traces,
                             cap=2 if spec == "draft" else 1,
                             registered=True)
        if self._quantize_fn is not None:
            self._sentinel.watch(
                "quantize", lambda: self.quantize_traces,
                cap=int(self._quant_target) + int(self._quant_draft),
                registered=True)
        if spec is not None:
            self._sentinel.watch("verify", lambda: self.verify_traces,
                                 cap=1, registered=True)
        if spec == "draft":
            self._sentinel.watch("draft", lambda: self.draft_traces,
                                 cap=1, registered=True)
            self._sentinel.watch("draft_prefill",
                                 lambda: self.draft_prefill_traces,
                                 registered=True)
        self._sentinel.watch("prefill", lambda: self.prefill_traces,
                             registered=True)
        # Block gather/scatter trace once per pool geometry: the draft
        # pool's shapes differ from the target's, so a draft engine gets
        # two traces; everyone else exactly one.
        self._sentinel.watch("kv_gather", lambda: self.kv_gather_traces,
                             cap=2 if spec == "draft" else 1,
                             registered=True)
        self._sentinel.watch("kv_scatter",
                             lambda: self.kv_scatter_traces,
                             cap=2 if spec == "draft" else 1,
                             registered=True)
        _telemetry.register_stats_source(self.name, self, kind="engine")

    def arm_retrace_sentinel(self):
        """Declare shape warmup over: every watched compile path —
        including the bucket-dependent prefill ones — is baselined at
        its current trace count, and ANY further trace increments
        `retraces_unexpected` and WARNs. The hard-capped paths (decode,
        verify, swap) are watched from construction regardless."""
        self._sentinel.arm()

    # ------------------------------------------------------------------
    # watchdog + admission shedding
    # ------------------------------------------------------------------

    def _watchdog_loop(self) -> None:
        """Detect a stuck scheduler tick: sample the in-progress tick's
        start time (lock-free reads — the watchdog must keep working
        precisely when the lock holder is wedged) and count + WARN once
        per tick that overruns the budget."""
        flagged = -1
        while not self._watchdog_stop.wait(self._watchdog_s / 4):
            started, seq = self._tick_started, self._tick_seq
            if (started is not None and seq != flagged
                    and time.perf_counter() - started > self._watchdog_s):
                flagged = seq
                self._watchdog_stalls += 1
                logger.warning(
                    "engine %s: scheduler tick %d stuck for > %.2fs",
                    getattr(self, "name", "?"), seq, self._watchdog_s)

    def _shed_verdict(self, n_blocks: int) -> str | None:
        """Overload decision for one admission of `n_blocks` footprint;
        called under the lock. None = admit; else the reason string."""
        if self.max_queue is not None and \
                len(self._pending) >= self.max_queue:
            return (f"queue full ({len(self._pending)} >= "
                    f"max_queue {self.max_queue})")
        if self.shed_high_water is not None:
            # Projected utilization: live blocks + the committed
            # footprints already queued + this request. Using the
            # projection (not just instantaneous usage) keeps a burst of
            # submits between two ticks from overshooting the mark.
            queued = sum(
                self._slot_blocks_for(q.prompt.size, q.max_new_tokens)
                for q in self._pending)
            projected = (self._alloc.used + queued + n_blocks) \
                / max(self.cache_blocks, 1)
            if projected > self.shed_high_water:
                return (f"projected block utilization {projected:.2f} > "
                        f"high water {self.shed_high_water:.2f}")
        return None

    # ------------------------------------------------------------------
    # request side
    # ------------------------------------------------------------------

    def _blocks_for(self, p: int, max_new: int) -> int:
        """Blocks a request's full footprint needs: prefill writes
        positions 0..p-1, decode writes p..p+max_new-2 (the final
        sampled token is never written)."""
        highest = p - 1 + max(max_new - 1, 0)
        return highest // self.block_size + 1

    def _slot_blocks_for(self, p: int, max_new: int) -> int:
        """Blocks THIS engine must hold for a request. A prefill-role
        engine never decodes: its slots only write the prompt's
        positions before handing off, so its footprint is the prompt
        blocks alone — the generation footprint is the importing
        engine's problem. Every other role needs the full
        prompt+generation footprint (`_blocks_for`)."""
        if self.role == "prefill":
            return (p - 1) // self.block_size + 1
        return self._blocks_for(p, max_new)

    def submit(self, prompt, max_new_tokens: int = 16,
               temperature: float = 0.0,
               eos_id: int | None = None,
               priority: int = 0) -> int:
        """Queue a prompt (sequence of token ids); returns a request id
        for `tokens_for`. Admission happens inside `step()` — long
        prompts are absorbed in chunks, so there is no per-bucket prompt
        length limit, only the cache-capacity ones.

        `priority` is the request's class (0 = lowest, up to
        ``priority_classes - 1``): higher classes get proportionally
        more admission share, shed last, and may preempt strictly-lower
        active streams under block pressure."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        priority = int(priority)
        if not 0 <= priority < self.priority_classes:
            raise ValueError(
                f"priority {priority} outside "
                f"[0, {self.priority_classes})")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds cache max_len {self.max_len}")
        if self._slot_blocks_for(prompt.size, max_new_tokens) > \
                self.cache_blocks:
            raise ValueError(
                f"request footprint "
                f"{self._slot_blocks_for(prompt.size, max_new_tokens)} "
                f"blocks exceeds cache blocks {self.cache_blocks}")
        if self._draft_alloc is not None and \
                self._slot_blocks_for(prompt.size, max_new_tokens) > \
                self.draft_cache_blocks:
            raise ValueError(
                f"request footprint exceeds draft cache blocks "
                f"{self.draft_cache_blocks}")
        with self._lock:
            if self.max_queue is not None or \
                    self.shed_high_water is not None:
                reason = self._shed_verdict(
                    self._slot_blocks_for(prompt.size, max_new_tokens))
                # Class-ordered shedding: pressure evicts the lowest-
                # class QUEUED request first; the incoming request is
                # only shed when nothing queued ranks below it (so an
                # all-one-class engine behaves exactly as before).
                while reason is not None and \
                        self._shed_lowest_below(priority):
                    reason = self._shed_verdict(
                        self._slot_blocks_for(prompt.size,
                                              max_new_tokens))
                if reason is not None:
                    self._sheds += 1
                    self._class_counter(priority)["sheds"] += 1
                    raise OverloadedError(
                        f"engine overloaded, request shed: {reason}")
            rid = self._rid
            self._rid += 1
            self._out[rid] = collections.deque()
            self._pending.append(_Pending(rid, prompt, max_new_tokens,
                                          temperature, eos_id,
                                          time.perf_counter(),
                                          priority=priority))
            self._class_counter(priority)["submitted"] += 1
            self._recorder.on_submit(rid, prompt.size)
        return rid

    def _shed_lowest_below(self, priority: int) -> bool:
        """Shed the lowest-class queued request strictly below
        `priority` (newest of that class — least sunk wait), delivering
        a typed `OverloadedError` through its `tokens_for`. Returns
        False when no queued request ranks below `priority`. Resumed
        (preempted) streams are never shed here: they have already
        delivered tokens to a live consumer."""
        victim_i = None
        for i, q in enumerate(self._pending):
            if q.priority >= priority or q.resumed:
                continue
            if victim_i is None:
                victim_i = i
                continue
            v = self._pending[victim_i]
            if (q.priority, -q.ts) < (v.priority, -v.ts):
                victim_i = i
        if victim_i is None:
            return False
        victim = self._pending[victim_i]
        del self._pending[victim_i]
        self._errors[victim.rid] = OverloadedError(
            f"engine overloaded: request (class {victim.priority}) "
            f"shed from the queue for a class-{priority} admission")
        self._sheds += 1
        self._class_counter(victim.priority)["sheds"] += 1
        self._recorder.on_finish(victim.rid, "shed")
        return True

    def _class_counter(self, c: int) -> dict:
        """Per-class counter row (lazily created; under the lock)."""
        d = self._per_class.get(c)
        if d is None:
            d = {"submitted": 0, "completed": 0, "sheds": 0,
                 "preemptions": 0, "decode_tokens": 0}
            self._per_class[c] = d
            self._class_waits[c] = collections.deque(maxlen=256)
        return d

    def cancel(self, rid: int) -> bool:
        """Abort a request wherever it is — pending, mid-prefill,
        decoding, or finished-but-undrained — releasing its cache
        blocks and output queue. Idempotent; returns True if anything
        was released."""
        with self._lock:
            hit = False
            for i, req in enumerate(self._pending):
                if req.rid == rid:
                    del self._pending[i]
                    hit = True
                    break
            for i, s in enumerate(self._slots):
                if s.rid == rid:
                    self._release(i)
                    hit = True
                    break
            if self._handoffs.pop(rid, None) is not None:
                # an exported-but-never-collected prefill: the device
                # blocks were already freed at export, so abandoning
                # only drops the host blob
                self._handoffs_abandoned += 1
                hit = True
            if rid in self._import_rids:
                self._import_rids.discard(rid)
                for i, (irid, _) in enumerate(self._imports):
                    if irid == rid:
                        del self._imports[i]
                        break
                hit = True
            hit |= self._out.pop(rid, None) is not None
            hit |= self._errors.pop(rid, None) is not None
            self._done.discard(rid)
            if hit:
                self._cancelled += 1
                self._recorder.on_finish(rid, "cancelled")
            return hit

    def tokens_for(self, rid: int):
        """Generator of generated tokens for one request — each yielded
        value is a `TokenEvent`: an ``int`` (token id) that also carries
        ``.logprob`` (natural log pi(token|prefix) under the weights it
        was sampled with) and ``.params_version``. Pumps the
        shared engine: each next() ticks `step()` (under the lock) until
        this request has output, so N concurrent consumers collectively
        drive one continuously-batched device loop. Abandoning the
        generator (break / close / GC) cancels the request and releases
        its cache blocks."""
        try:
            while True:
                tok = None
                fin = False
                with self._lock:   # pop under the lock, yield OUTSIDE
                    # it — a generator suspends at yield, and a
                    # suspended holder would block every consumer's pump.
                    q = self._out.get(rid)
                    if q is None:
                        return
                    err = self._errors.pop(rid, None)
                    if err is not None:
                        # terminated while queued (class-ordered shed):
                        # surface the typed error to this consumer
                        del self._out[rid]
                        self._done.discard(rid)
                        raise err
                    if not q and rid not in self._done:
                        # ONE tick per lock hold, not a hold-until-token
                        # loop: releasing between ticks lets submit()/
                        # stats()/cancel() interleave with saturated
                        # pumps. (Observed: the serve controller's
                        # autoscaling scrape starving seconds behind 8
                        # pumping consumers and reading post-drain
                        # queue depths — the scale-up signal vanished.)
                        self.step()
                    if q:
                        tok = q.popleft()
                    if rid in self._done and not q:
                        self._done.discard(rid)
                        del self._out[rid]
                        fin = True
                if tok is not None:
                    yield tok
                elif fin:
                    return
        finally:
            self.cancel(rid)

    def generate(self, prompt, **kw) -> list[int]:
        """Blocking convenience: submit + drain one request."""
        return list(self.tokens_for(self.submit(prompt, **kw)))

    # ------------------------------------------------------------------
    # disaggregated prefill/decode handoff
    # ------------------------------------------------------------------

    def _export_handoff(self, slot_idx: int):
        """Prefill-role endgame for one slot (under the lock, called
        from `_run_prefill_chunk` the tick the prompt completes): gather
        every written KV block — payload and any int8 scale rows travel
        together, block-aligned — to host, park the blob for collection,
        and free the device blocks. The blob carries everything a
        decode-role `import_handoff` needs to continue the stream
        token-identically: the parked first token (with logprob/version,
        sampled from the final prefill chunk HERE so the decode engine
        never re-runs prefill), the sampling state, and the weight
        version the KV was computed under."""
        s = self._slots[slot_idx]
        p = s.prompt.size
        n_written = (p - 1) // self.block_size + 1
        t0 = time.perf_counter()

        def _dump(pool, blocks):
            out = []
            for b in blocks[:n_written]:
                blk = self._gather_fn(pool, np.int32(b))
                # graftlint: disable-next-line=R001,R004 the export IS the handoff's one deliberate device->host pull: the blob must be host bytes before it can ride netaddr to the decode replica
                out.append({k: np.asarray(v) for k, v in blk.items()})
            return out

        payload = _dump(self.cache, s.blocks)
        draft_payload = (_dump(self.draft_cache, s.draft_blocks)
                         if self._draft_alloc is not None else None)
        kv_bytes = sum(int(a.nbytes) for blk in payload
                       for a in blk.values())
        if draft_payload is not None:
            kv_bytes += sum(int(a.nbytes) for blk in draft_payload
                            for a in blk.values())
        dt = time.perf_counter() - t0
        blob = {
            "rid": s.rid,
            "prompt": s.prompt,
            "token": int(s.token),
            "token_logp": float(s.token_logp),
            "token_ver": int(s.token_ver),
            "max_new_tokens": int(s.remaining),
            "temperature": float(s.temperature),
            "eos_id": s.eos_id,
            "priority": int(s.priority),
            "params_version": int(self._params_version),
            "block_size": self.block_size,
            "n_blocks": n_written,
            "payload": payload,
            "draft_payload": draft_payload,
            "kv_bytes": int(kv_bytes),
        }
        self._handoffs[s.rid] = blob
        self._handoffs_exported += 1
        self._kv_blocks_exported += n_written * (
            2 if draft_payload is not None else 1)
        self._kv_export_bytes += kv_bytes
        self._kv_export_ms.append(dt * 1e3)
        self._recorder.on_kv_export(s.rid, n_written, kv_bytes, dt)
        self._recorder.on_finish(s.rid, "handoff")
        # No token consumer on a prefill engine: drop the output queue
        # now (handoff_for polls `_handoffs`, not `_out`) and release
        # the device blocks — the prompt's full blocks live on in the
        # radix tree for shared-prefix admissions, everything else is
        # host-side in the blob.
        self._out.pop(s.rid, None)
        self._done.discard(s.rid)
        self._release(slot_idx)

    def handoff_for(self, rid: int) -> dict:
        """Pump the scheduler until `rid`'s prefill completes, then pop
        and return its handoff blob — the prefill-role analogue of
        draining `tokens_for`. Raises the parked error for a request
        shed from the queue, KeyError for an unknown/cancelled rid."""
        if self.role != "prefill":
            raise RuntimeError(
                "handoff_for is only available on a prefill-role "
                f"engine (this engine is {self.role!r})")
        while True:
            with self._lock:
                blob = self._handoffs.pop(rid, None)
                if blob is not None:
                    return blob
                err = self._errors.pop(rid, None)
                if err is not None:
                    self._out.pop(rid, None)
                    self._done.discard(rid)
                    raise err
                if rid not in self._out:
                    raise KeyError(
                        f"unknown or cancelled handoff rid {rid}")
                # one tick per lock hold, same contract as tokens_for
                self.step()

    def take_handoff(self, rid: int) -> dict | None:
        """Non-blocking collect: pop `rid`'s parked blob if its prefill
        already completed, else None."""
        with self._lock:
            return self._handoffs.pop(rid, None)

    def import_handoff(self, blob: dict) -> int:
        """Adopt a prefill-role engine's handoff blob: queue its KV
        blocks for scatter into this pool and its stream for a decode
        slot. Returns a fresh LOCAL rid for `tokens_for` — the stream
        picks up at the first generated token (already sampled by the
        prefill engine and delivered from here), greedy token-identical
        to a colocated run over the same prompt."""
        if self.role == "prefill":
            raise RuntimeError(
                "a prefill-role engine cannot import handoffs")
        prompt = np.asarray(blob["prompt"], np.int32).reshape(-1)
        p = prompt.size
        max_new = int(blob["max_new_tokens"])
        if int(blob["block_size"]) != self.block_size:
            raise ValueError(
                f"handoff block_size {blob['block_size']} != engine "
                f"block_size {self.block_size} — prefill and decode "
                f"pools must share the paging granule")
        n_written = (p - 1) // self.block_size + 1
        if len(blob["payload"]) != n_written:
            raise ValueError(
                f"handoff payload has {len(blob['payload'])} blocks, "
                f"expected {n_written} for a {p}-token prompt")
        if p + max_new > self.max_len:
            raise ValueError(
                f"handoff prompt {p} + max_new_tokens {max_new} "
                f"exceeds cache max_len {self.max_len}")
        if self._blocks_for(p, max_new) > self.cache_blocks:
            raise ValueError(
                f"handoff footprint {self._blocks_for(p, max_new)} "
                f"blocks exceeds cache blocks {self.cache_blocks}")
        if self._draft_alloc is not None:
            if blob.get("draft_payload") is None:
                raise ValueError(
                    "draft-spec engine needs the handoff's draft-pool "
                    "blocks (prefill engine must run the same spec)")
            if self._blocks_for(p, max_new) > self.draft_cache_blocks:
                raise ValueError(
                    "handoff footprint exceeds draft cache blocks "
                    f"{self.draft_cache_blocks}")
        priority = int(blob.get("priority", 0))
        if not 0 <= priority < self.priority_classes:
            raise ValueError(
                f"handoff priority {priority} outside "
                f"[0, {self.priority_classes})")
        with self._lock:
            rid = self._rid
            self._rid += 1
            self._out[rid] = collections.deque()
            self._imports.append((rid, blob))
            self._import_rids.add(rid)
            self._class_counter(priority)["submitted"] += 1
            self._recorder.on_submit(rid, p)
        return rid

    def _admit_imports(self) -> bool:
        """Move queued handoff imports into decode slots (FIFO), ahead
        of regular pending admissions — an import's prefill cost is
        already sunk on another engine, so making it wait behind local
        prefills would throw that work away latency-wise. Under block
        pressure an import may preempt strictly-lower-class active
        streams, exactly like `_admit_or_preempt`."""
        did = False
        while self._imports:
            rid, blob = self._imports[0]
            free = next((i for i, s in enumerate(self._slots)
                         if s.phase == "idle"), None)
            if free is None:
                break
            if not self._try_import(free, rid, blob):
                victim = self._pick_victim(int(blob.get("priority", 0)))
                if victim is None:
                    break
                self._preempt(victim, "import-pressure")
                continue
            self._imports.popleft()
            self._import_rids.discard(rid)
            did = True
        return did

    def _try_import(self, slot_idx: int, rid: int, blob: dict) -> bool:
        """Install one handoff into a slot: share any radix-cached full
        prefix blocks by reference, scatter the remaining transferred
        blocks into freshly allocated ones, and enter the decode phase
        at the first generated token. Returns False (leaving the import
        queued) when the pool can't supply the footprint even after
        eviction. Unlike `_try_admit` there is NO copy-on-write: a
        prefix match ending mid-block just means that block is
        re-scattered from the transferred payload instead of shared —
        cheaper than a device copy and bit-identical by construction."""
        bs = self.block_size
        # graftlint: disable-next-line=R001,R004 blob arrays are host numpy (they crossed the wire); this asarray is a view/cast, not a device sync
        prompt = np.asarray(blob["prompt"], np.int32).reshape(-1)
        p = prompt.size
        max_new = int(blob["max_new_tokens"])
        total = self._blocks_for(p, max_new)
        payload = blob["payload"]
        n_written = len(payload)
        try:
            _faults.check("engine.alloc")
        except _faults.FaultInjected:
            return False
        if self._draft_alloc is not None and \
                self._draft_alloc.free < total:
            return False
        # Prefix sharing only under a matching weight version: imported
        # KV was computed under the blob's params_version, and mixing it
        # with tree blocks from a different version would splice stale
        # context into the sequence.
        blocks, matched = ([], 0)
        if self._tree is not None and \
                int(blob["params_version"]) == self._params_version:
            blocks, matched = self._tree.match(prompt)
        n_full = min(matched // bs, n_written)
        for b in blocks[:n_full]:
            self._alloc.ref(b)
        fresh_needed = total - n_full
        if self._alloc.free < fresh_needed and self._tree is not None:
            self._evicted_blocks += self._tree.evict(
                fresh_needed - self._alloc.free)
        if self._alloc.free < fresh_needed:
            for b in blocks[:n_full]:
                self._alloc.decref(b)
            return False
        fresh = [self._alloc.alloc() for _ in range(fresh_needed)]
        slot_blocks = blocks[:n_full] + fresh
        jnp = self._jax.numpy
        t0 = time.perf_counter()
        scattered = 0
        for j in range(n_full, n_written):
            self.cache = self._scatter_block_fn(
                self.cache,
                {k: jnp.asarray(v) for k, v in payload[j].items()},
                np.int32(slot_blocks[j]))
            scattered += 1
        table = np.zeros((self.max_blocks,), np.int32)
        table[:len(slot_blocks)] = slot_blocks
        s = self._slots[slot_idx]
        s.rid, s.phase = rid, "decode"
        s.prompt, s.filled = prompt, p
        s.blocks, s.table = slot_blocks, table
        s.order = self._admit_seq
        self._admit_seq += 1
        s.temperature = float(blob["temperature"])
        s.eos_id = blob["eos_id"]
        s.remaining = max_new - 1
        s.pos = p
        s.token = int(blob["token"])
        s.token_logp = float(blob["token_logp"])
        s.token_ver = int(blob["token_ver"])
        s.submit_ts = time.perf_counter()
        s.priority = int(blob.get("priority", 0))
        # resumed: TTFT was recorded on the prefill engine — counting
        # the import here would double-book the same first token.
        s.resumed = True
        s.emitted = []
        s.history = prompt.tolist() if self.spec == "ngram" else []
        if self._draft_alloc is not None:
            dblocks = [self._draft_alloc.alloc() for _ in range(total)]
            dtable = np.zeros((self.max_blocks,), np.int32)
            dtable[:len(dblocks)] = dblocks
            for j in range(n_written):
                self.draft_cache = self._scatter_block_fn(
                    self.draft_cache,
                    {k: jnp.asarray(v)
                     for k, v in blob["draft_payload"][j].items()},
                    np.int32(dblocks[j]))
                scattered += 1
            s.draft_blocks, s.draft_table = dblocks, dtable
            s.draft_filled = p
        # Version trust: a same-version import's prompt blocks are as
        # publishable as a local prefill's; a cross-version one must
        # never enter the tree (its K/V predates the current weights).
        if int(blob["params_version"]) == self._params_version:
            s.version = self._params_version
            if self._tree is not None and p >= bs:
                self._tree.insert(prompt, slot_blocks)
        else:
            s.version = self._params_version - 1
        dt = time.perf_counter() - t0
        kv_bytes = int(blob.get("kv_bytes", 0))
        self._imports_completed += 1
        self._kv_blocks_imported += scattered
        self._kv_import_bytes += kv_bytes
        self._kv_import_ms.append(dt * 1e3)
        self._prefix_hit_tokens += n_full * bs
        self._prompt_tokens += p
        self._recorder.on_kv_import(rid, scattered, kv_bytes, dt)
        self._recorder.on_admit(rid, n_full * bs, False)
        # Deliver the parked first token through the normal emit path
        # (it carries the logprob/version the prefill engine computed);
        # a max_new_tokens=1 request retires right here.
        self._emit(s, slot_idx, s.token, s.token_logp, s.token_ver)
        return True

    # ------------------------------------------------------------------
    # weight hot-swap (RL flywheel)
    # ------------------------------------------------------------------

    def _place_tree(self, old, new, what: str):
        """Validate leaf-for-leaf compatibility and place `new` on the
        old leaves' shardings. Pure host+transfer work against a
        *snapshot* of the old tree — runs under the swap mutex only,
        never the scheduler lock, so ticks proceed during the upload."""
        jax = self._jax
        old_leaves, old_def = jax.tree.flatten(old)
        new_leaves, new_def = jax.tree.flatten(new)
        if old_def != new_def:
            raise ValueError(
                f"update_params: {what} pytree structure changed "
                f"({new_def} != {old_def})")
        for o, n in zip(old_leaves, new_leaves):
            if tuple(o.shape) != tuple(n.shape) or o.dtype != n.dtype:
                raise ValueError(
                    f"update_params: {what} leaf mismatch "
                    f"{n.shape}/{n.dtype} != {o.shape}/{o.dtype} — "
                    f"hot-swap requires identical shapes and dtypes")
        return jax.tree.unflatten(old_def, [
            jax.device_put(n, o.sharding) if hasattr(o, "sharding")
            else jax.numpy.asarray(n)
            for o, n in zip(old_leaves, new_leaves)])

    def update_params(self, new_params, *, draft_params=None) -> int:
        """Hot-swap model weights into the live engine between ticks.

        `new_params` must match the current params pytree leaf-for-leaf
        in structure, shape, and dtype (optimizer steps preserve this by
        construction). The swap is an in-place donated device copy into
        the OLD buffers, so nothing the compiled decode / verify / prefill
        executables depend on changes: trace counters stay untouched —
        asserted in tests — and in-flight requests are not restarted.
        `draft_params` optionally swaps the speculative draft model the
        same way.

        Consequences the caller should know:

        - The engine owns its buffers: the params object passed at
          construction (or returned by a previous swap) is invalidated
          by donation. `new_params` itself is NOT donated — a trainer
          can keep training on the same state it published.
        - `weight_dtype="int8"` engines still take f32 masters here:
          the same jitted quantization that ran at construction re-runs
          on the published tree before validation/placement, so the RL
          flywheel never handles int8 and the swap stays retrace-free.
        - The radix prefix cache is flushed: cached K/V was computed
          under the old weights and must not be shared into post-swap
          admissions. In-flight sequences keep their already-written
          K/V and finish on mixed old/new-weight context — the standard
          in-place-sync staleness tradeoff (MindSpeed RL, 2507.19017) —
          which the per-token `params_version` tags make visible so
          learners can bound staleness or importance-correct.
        - `params_version` increments and stamps every subsequently
          computed token (`TokenEvent.params_version`); `stats()`
          reports it alongside the `swaps` counter and `weight_swap_ms`
          (update_params call to first post-swap token).

        Returns the new `params_version`."""
        # Swappers serialize on the swap mutex; the scheduler lock is
        # held only for the two brief sections that touch engine state
        # (snapshot, commit). Validation and the host->device upload of
        # the new tree — the slow part — happen between them, so decode
        # ticks keep running while weights stream in (R004: the swap
        # mutex is declared blocking_ok for exactly this).
        with self._swap_mutex:
            t0 = time.perf_counter()
            with self._lock:
                old = self.params
                old_draft = self.draft_params
            if draft_params is not None and old_draft is None:
                raise ValueError(
                    "update_params: draft_params given but the "
                    "engine has no draft model")
            # Int8 weight-only engines hold quantized trees: quantize
            # the published f32 masters BEFORE validation, so the
            # leaf-for-leaf check compares quantized against quantized
            # and the donated swap copies int8+scales. Shapes repeat, so
            # this hits the cached _quantize trace (quantize_traces is
            # sentinel-pinned).
            if self._quant_target:
                new_params = self._quantize_fn(new_params)
            if self._quant_draft and draft_params is not None:
                draft_params = self._quantize_fn(draft_params)
            placed = self._place_tree(old, new_params, "params")
            placed_draft = (
                self._place_tree(old_draft, draft_params, "draft_params")
                if draft_params is not None else None)
            with self._lock:
                self.params = self._swap_fn(old, placed)
                if placed_draft is not None:
                    self.draft_params = self._swap_fn(
                        old_draft, placed_draft)
                if self._tree is not None:
                    self._tree.flush()
                self._params_version += 1
                self._swaps += 1
                self._swap_pending_ts = t0
                return self._params_version

    @property
    def params_version(self) -> int:
        return self._params_version

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def _chunk_bucket_for(self, n: int) -> int:
        for b in self.chunk_buckets:
            if n <= b:
                return b
        raise ValueError(f"no chunk bucket for length {n}")

    def _release(self, slot_idx: int):
        s = self._slots[slot_idx]
        for b in s.blocks:
            self._alloc.decref(b)
        for b in s.draft_blocks:
            self._draft_alloc.decref(b)
        self._slots[slot_idx] = _Slot()

    def _try_admit(self, slot_idx: int, req: _Pending) -> bool:
        """Allocate a slot's blocks (sharing any cached prefix) and put
        it in the prefill phase. Returns False — leaving the request
        pending — if the pool can't supply the footprint even after
        evicting zero-ref cached prefixes."""
        bs = self.block_size
        p = req.prompt.size
        total = self._slot_blocks_for(p, req.max_new_tokens)
        # fault site: 'fail' here reads as deterministic allocator
        # exhaustion — the admission is refused exactly as if the pool
        # had no free blocks, driving the class-preemption path (it
        # does NOT unwind to the consumer)
        try:
            _faults.check("engine.alloc")
        except _faults.FaultInjected:
            return False
        # The draft pool has no prefix sharing or eviction — the full
        # footprint must be free up front, checked before any main-pool
        # work so failure needs no rollback.
        if self._draft_alloc is not None and \
                self._draft_alloc.free < total:
            return False
        blocks, matched = ([], 0)
        if self._tree is not None:
            blocks, matched = self._tree.match(req.prompt)
        # Always leave >= 1 token to prefill: the request's first
        # generated token is sampled from its final prefill chunk.
        matched = min(matched, p - 1)
        blocks = blocks[:-(-matched // bs)] if matched else []
        n_full = matched // bs
        partial = matched % bs != 0
        # Reference the shared blocks BEFORE any eviction so the tree
        # can't free them out from under this admission.
        for b in blocks:
            self._alloc.ref(b)
        fresh_needed = total - n_full
        if self._alloc.free < fresh_needed and self._tree is not None:
            self._evicted_blocks += self._tree.evict(
                fresh_needed - self._alloc.free)
        if self._alloc.free < fresh_needed:
            for b in blocks:
                self._alloc.decref(b)
            return False
        fresh = [self._alloc.alloc() for _ in range(fresh_needed)]
        slot_blocks = blocks[:n_full] + fresh
        if partial:
            # Copy-on-write: the matched prefix ends inside a shared
            # block; this request's own tokens land in that block, so
            # copy it into a private one first.
            src, dst = blocks[n_full], fresh[0]
            self.cache = self._copy_fn(self.cache, np.int32(src),
                                       np.int32(dst))
            self._cow_copies += 1
            self._alloc.decref(src)
        table = np.zeros((self.max_blocks,), np.int32)
        table[:len(slot_blocks)] = slot_blocks
        s = self._slots[slot_idx]
        s.rid, s.phase = req.rid, "prefill"
        s.prompt, s.filled = req.prompt, matched
        s.blocks, s.table = slot_blocks, table
        s.order = self._admit_seq
        self._admit_seq += 1
        s.temperature, s.eos_id = req.temperature, req.eos_id
        s.remaining = req.max_new_tokens
        s.submit_ts = req.ts
        s.version = self._params_version
        s.priority = req.priority
        s.resumed = req.resumed
        s.emitted = []
        if req.resumed:
            # blocks' worth of KV this resume recomputes (the radix
            # match absorbed the rest for free)
            self._reprefill_blocks += -(-(p - matched) // bs)
        s.history = req.prompt.tolist() if self.spec == "ngram" else []
        if self._draft_alloc is not None:
            dblocks = [self._draft_alloc.alloc() for _ in range(total)]
            dtable = np.zeros((self.max_blocks,), np.int32)
            dtable[:len(dblocks)] = dblocks
            s.draft_blocks, s.draft_table = dblocks, dtable
            s.draft_filled = 0
        self._prefix_hit_tokens += matched
        self._prompt_tokens += p
        self._recorder.on_admit(req.rid, matched, partial)
        return True

    def _admission_order(self) -> list[_Pending]:
        """Class-aware admission order over the pending queue.

        Two mechanisms compose (ROADMAP item 4's multi-tenant
        admission): **weighted shares** — a stride scheduler across
        backlogged classes with weight ``priority_weight_base**class``,
        so class c+1 gets base x class c's admission share while every
        backlogged class keeps a guaranteed nonzero share — and
        **aging** — a request older than
        ``(priority_classes - class) * priority_aging_s`` escalates
        past the stride order entirely (oldest first), which bounds the
        worst-case wait of the lowest class under sustained high-class
        load. Within one class, order is FIFO."""
        now = time.perf_counter()
        aged: list[_Pending] = []
        backlog: dict[int, collections.deque] = {}
        for req in self._pending:
            bound = (self.priority_classes - req.priority) \
                * self.priority_aging_s
            if now - req.ts > bound:
                if not req.aged:
                    req.aged = True
                    self._aging_promotions += 1
                aged.append(req)
            else:
                backlog.setdefault(
                    req.priority, collections.deque()).append(req)
        aged.sort(key=lambda r: (r.ts, r.rid))
        order = aged
        # A class entering the backlog starts at the current pass floor
        # so it can't claim banked credit for the time it was idle.
        floor = max(self._class_pass.values(), default=0.0)
        for c in backlog:
            self._class_pass.setdefault(c, floor)
        sim = dict(self._class_pass)
        while backlog:
            c = min(backlog, key=lambda k: (sim[k], -k))
            order.append(backlog[c].popleft())
            sim[c] += 1.0 / (self.priority_weight_base ** c)
            if not backlog[c]:
                del backlog[c]
        return order

    def _pick_victim(self, below: int) -> int | None:
        """Preemption victim: the active slot of the lowest class
        strictly below `below`; ties broken by most recent admission
        (least progress = cheapest re-prefill)."""
        best = None
        for i, s in enumerate(self._slots):
            if not s.active or s.priority >= below:
                continue
            if best is None or \
                    (s.priority, -s.order) < (self._slots[best].priority,
                                              -self._slots[best].order):
                best = i
        return best

    def _preempt(self, slot_idx: int, why: str) -> None:
        """Evict one active stream under block pressure: publish its
        written blocks to the radix tree (resume admits them by
        reference — mostly free), release the slot, and requeue
        prompt+emitted as a re-prefill under the SAME rid and output
        queue. The consumer keeps iterating `tokens_for` unaware; a
        greedy stream resumes token-identical because the re-prefilled
        KV is bit-identical to the KV released (prefill and decode
        share the paged attention math)."""
        s = self._slots[slot_idx]
        seq = [int(t) for t in s.prompt.tolist()] \
            + [int(t) for t in s.emitted]
        # KV written so far covers seq[:pos] in decode (the parked
        # last token is sampled but never written), prompt[:filled]
        # mid-prefill.
        written = s.pos if s.phase == "decode" else s.filled
        if self._tree is not None and written >= self.block_size \
                and s.version == self._params_version:
            self._tree.insert(seq[:written], s.blocks)
        resume = _Pending(
            s.rid,
            np.concatenate([
                s.prompt.astype(np.int32, copy=False),
                np.fromiter((int(t) for t in s.emitted), np.int32,
                            len(s.emitted))]),
            s.remaining, s.temperature, s.eos_id, s.submit_ts,
            priority=s.priority, resumed=True)
        self._preemptions += 1
        self._class_counter(s.priority)["preemptions"] += 1
        logger.info(
            "engine %s: preempted rid=%d class=%d (%s) after %d tokens",
            getattr(self, "name", "?"), s.rid, s.priority, why,
            len(s.emitted))
        self._recorder.on_finish(s.rid, f"preempted:{why}")
        self._release(slot_idx)
        self._pending.appendleft(resume)

    def _force_preempt(self) -> bool:
        """Fault-injected preemption (site ``engine.preempt``): evict
        the lowest-class active stream regardless of pressure."""
        victim = self._pick_victim(self.priority_classes)
        if victim is None:
            return False
        self._preempt(victim, "forced")
        return True

    def _admit_or_preempt(self, req: _Pending) -> bool:
        """Admit one request, preempting strictly-lower-class active
        streams while the block pool can't serve it. Slot exhaustion
        defers instead of preempting: the stride order already decided
        who deserves the slots, and letting a later entry evict this
        pass's winners would undo the weighted shares (observed as
        full class-1 drain before any class-0 admission). Bounded:
        every retry removes one active victim."""
        free = next((i for i, s in enumerate(self._slots)
                     if s.phase == "idle"), None)
        if free is None:
            return False
        while not self._try_admit(free, req):
            victim = self._pick_victim(req.priority)
            if victim is None:
                return False
            self._preempt(victim, "block-pressure")
        return True

    def _admit_pending(self) -> bool:
        """Move pending requests into slots, in class-aware order
        (`_admission_order`). A request whose first block of tokens
        matches an in-flight prefill's is deferred one tick — once that
        prefill completes and its full blocks enter the radix tree, the
        latecomer admits by reference instead of re-prefilling the
        shared prefix. When a request fails admission even after
        preemption, strictly LOWER classes are locked out for the rest
        of the tick — freed blocks accrue to the blocked class instead
        of leaking to small low-class requests forever."""
        if not self._pending:
            return False
        bs = self.block_size
        heads = set()
        if self._tree is not None:
            heads = {tuple(s.prompt[:bs].tolist())
                     for s in self._slots
                     if s.phase == "prefill" and s.prompt.size >= bs}
        order = self._admission_order()
        # Reset the live queue: preemptions during the loop appendleft
        # their resumes here (re-admitted next tick); deferred requests
        # are re-extended below.
        self._pending = collections.deque()
        admitted, keep = False, []
        blocked_pri: int | None = None
        for req in order:
            head = (tuple(req.prompt[:bs].tolist())
                    if req.prompt.size >= bs else None)
            if head is not None and head in heads \
                    and self._tree is not None:
                keep.append(req)
                continue
            if blocked_pri is not None and req.priority < blocked_pri:
                keep.append(req)
                continue
            if self._admit_or_preempt(req):
                admitted = True
                self._class_pass[req.priority] = \
                    self._class_pass.get(req.priority, 0.0) \
                    + 1.0 / (self.priority_weight_base ** req.priority)
                if head is not None:
                    heads.add(head)
            else:
                keep.append(req)
                if blocked_pri is None or req.priority > blocked_pri:
                    blocked_pri = req.priority
        # keep is in admission order — per-class FIFO is preserved,
        # which is the only order the scheduler depends on. Preempted
        # resumes (appendleft during the loop) stay at the front.
        self._pending.extend(keep)
        # drop stride state for classes with no backlog left so a
        # long-idle class can't bank credit
        live = {q.priority for q in self._pending}
        for c in [c for c in self._class_pass if c not in live]:
            del self._class_pass[c]
        return admitted

    def _run_prefill_chunk(self, slot_idx: int):
        jnp = self._jax.numpy
        s = self._slots[slot_idx]
        if s.filled < s.prompt.size:
            clen = min(self.prefill_chunk, s.prompt.size - s.filled)
            cap = self._chunk_bucket_for(clen)
            toks = np.zeros((1, cap), np.int32)
            toks[0, :clen] = s.prompt[s.filled:s.filled + clen]
            t0 = time.perf_counter()
            tok, lp, self.cache = self._prefill_fn(
                self.params, jnp.asarray(toks), self.cache,
                jnp.asarray(s.table), np.int32(s.filled),
                np.int32(clen), np.float32(s.temperature),
                self._base_key, np.int32(self._decode_steps))
            # graftlint: disable-next-line=R001,R004 the chunk's one deliberate sync: the first token must reach the host to park on the slot, and syncing here keeps the prefill timing honest
            tok = int(tok)    # device sync, so the timing is honest
            dt = time.perf_counter() - t0
            self._prefill_time += dt
            self._recorder.on_prefill_chunk(s.rid, clen, cap, dt)
            self._prefill_tokens += clen
            self._prefill_chunks += 1
            s.filled += clen
            if s.filled >= s.prompt.size:
                # Park the first generated token (with its logprob and
                # compute-time version) until the draft cache (if any)
                # catches up and the slot joins decode.
                s.token = tok
                # graftlint: disable-next-line=R001,R004 lp is already on host after the int(tok) sync above; float() here is a cast, not a new device round-trip
                s.token_logp = float(lp)
                s.token_ver = self._params_version
        # Draft-model backend: the draft pool has no prefix sharing, so
        # it absorbs the FULL prompt through its own chunk loop — one
        # draft chunk per tick, alongside the main chunk. No host sync:
        # device dataflow orders these writes before the first propose.
        if self._draft_alloc is not None and \
                s.draft_filled < s.prompt.size:
            dclen = min(self.prefill_chunk,
                        s.prompt.size - s.draft_filled)
            dcap = self._chunk_bucket_for(dclen)
            dtoks = np.zeros((1, dcap), np.int32)
            dtoks[0, :dclen] = s.prompt[
                s.draft_filled:s.draft_filled + dclen]
            t0 = time.perf_counter()
            self.draft_cache = self._draft_prefill_fn(
                self.draft_params, jnp.asarray(dtoks), self.draft_cache,
                jnp.asarray(s.draft_table), np.int32(s.draft_filled),
                np.int32(dclen))
            self._prefill_time += time.perf_counter() - t0
            s.draft_filled += dclen
        if s.filled < s.prompt.size or (
                self._draft_alloc is not None
                and s.draft_filled < s.prompt.size):
            return
        # Prefill complete: publish the prompt's full blocks to the
        # radix tree (decode writes only past them, so they are
        # immutable), then join the decode batch. A slot admitted under
        # an older params_version spanned a hot-swap mid-prefill — its
        # K/V mixes weight versions and must NOT enter the prefix cache.
        if self._tree is not None and s.prompt.size >= self.block_size \
                and s.version == self._params_version:
            self._tree.insert(s.prompt, s.blocks)
        if self.role == "prefill":
            # Disaggregated handoff: the first token is sampled (TTFT
            # closes HERE — the decode side never re-counts it), then
            # the written blocks ship to host and the slot frees for
            # the next prompt. No decode phase ever runs on this
            # engine.
            if not s.resumed:
                wait = time.perf_counter() - s.submit_ts
                self._queue_waits.append(wait)
                self._class_waits[s.priority].append(wait)
                self._recorder.on_first_token(s.rid, wait)
            self._export_handoff(slot_idx)
            return
        s.phase = "decode"
        s.pos = s.prompt.size
        s.remaining -= 1
        if not s.resumed:
            # A resumed (preempted) stream delivered its first token
            # long ago — re-counting its original submit_ts here would
            # poison the TTFT/queue-wait percentiles.
            wait = time.perf_counter() - s.submit_ts
            self._queue_waits.append(wait)
            self._class_waits[s.priority].append(wait)
            self._recorder.on_first_token(s.rid, wait)
        self._emit(s, slot_idx, s.token, s.token_logp, s.token_ver)

    def _prefill_tick(self, had_decoders: bool) -> bool:
        """Run prefill chunks: at most ONE while anything is decoding
        (the per-tick admission budget that bounds decode stall); drain
        freely when the engine is otherwise idle — nobody is waiting."""
        did = False
        while True:
            prefilling = [i for i, s in enumerate(self._slots)
                          if s.phase == "prefill"]
            if not prefilling:
                return did
            prefilling.sort(key=lambda i: self._slots[i].order)
            self._run_prefill_chunk(prefilling[0])
            did = True
            if had_decoders:
                return did

    def _emit(self, s: _Slot, slot_idx: int, tok: int,
              logp: float = 0.0, ver: int | None = None):
        """Route one generated token (as a `TokenEvent` carrying its
        logprob and params_version); retire the slot (releasing its
        blocks) when finished."""
        # fault site: 'kill' here is the deterministic
        # kill-replica-at-step — the process dies between token N and
        # N+1, exactly what mid-stream failover must survive
        _faults.check("engine.emit")
        ev = TokenEvent(tok, logp,
                        self._params_version if ver is None else ver)
        if self._swap_pending_ts is not None:
            # First token computed after a hot-swap closes the
            # weight_swap_ms measurement window.
            self._last_swap_ms = (time.perf_counter()
                                  - self._swap_pending_ts) * 1e3
            self._swap_pending_ts = None
            self._recorder.on_swap_crossing(s.rid)
        self._out[s.rid].append(ev)
        s.emitted.append(int(tok))
        cc = self._class_counter(s.priority)
        cc["decode_tokens"] += 1
        self._recorder.on_token(s.rid)
        if self.spec == "ngram":
            s.history.append(tok)
        hit_eos = s.eos_id is not None and tok == s.eos_id
        # pos of the *next* token; it must still fit in the cache row.
        if s.remaining <= 0 or hit_eos or s.pos + 1 >= self.max_len:
            self._done.add(s.rid)
            cc["completed"] += 1
            self._release(slot_idx)
            self._recorder.on_finish(s.rid, "finished")

    def step(self) -> bool:
        """One scheduler tick: admit pending requests into free slots,
        run at most one prefill chunk if anything is decoding (all
        pending prefill work otherwise), then one decode step for every
        resident sequence. Returns True if any device work happened."""
        with self._lock:
            t_tick = time.perf_counter()
            # watchdog window: seq first, then start ts, cleared in the
            # finally — a fault-failed tick must not read as stuck forever
            self._tick_seq += 1
            self._tick_started = t_tick
            try:
                # fault site: 'fail' surfaces FaultInjected to the
                # pumping consumer; 'delay' wedges the tick (what the
                # watchdog exists to catch)
                _faults.check("engine.tick")
                # fault site: 'fail' forces preemption of the lowest-
                # class active stream this tick (absorbed — consumers
                # see only the token-identical resume)
                try:
                    _faults.check("engine.preempt")
                except _faults.FaultInjected:
                    self._force_preempt()
                had_decoders = any(
                    s.phase == "decode" for s in self._slots)
                imported = self._admit_imports()
                admitted = self._admit_pending() or imported
                chunked = self._prefill_tick(had_decoders)
                if had_decoders and (admitted or chunked):
                    self._max_admission_stall = max(
                        self._max_admission_stall,
                        time.perf_counter() - t_tick)
                active = [i for i, s in enumerate(self._slots)
                          if s.active]
                self._occupancy.append(len(active) / self.num_slots)
                self._block_util.append(
                    self._alloc.used / max(self.cache_blocks, 1))
                decoding = [i for i, s in enumerate(self._slots)
                            if s.phase == "decode"]
                if not decoding:  # idle, or admissions finished early
                    self._sentinel.check()
                    return admitted or chunked
                if self.spec is not None:
                    self._spec_tick(decoding)
                else:
                    self._decode_tick(decoding)
                self._sentinel.check()
                return True
            finally:
                self._tick_started = None

    def _dev(self, name: str, arr):
        """Host array -> device, through the replicated per-step input
        shardings when the engine runs on a mesh."""
        if self._io_sh is None:
            return self._jax.numpy.asarray(arr)
        # graftlint: disable-next-line=R004 µs-scale host->device placement of tiny per-tick inputs; placing outside the lock would race slot state, and the transfer is async (no sync back)
        return self._jax.device_put(arr, self._io_sh[name])

    def _batch_arrays(self):
        """Per-slot decode inputs. Rows not decoding (idle or
        mid-prefill) point at the trash block with pos 0: their garbage
        write collides harmlessly there and their sampled token is
        never read."""
        zeros = np.zeros((self.max_blocks,), np.int32)
        tokens = np.array(
            [s.token if s.phase == "decode" else 0
             for s in self._slots], np.int32)
        pos = np.array(
            [s.pos if s.phase == "decode" else 0
             for s in self._slots], np.int32)
        tables = np.stack(
            [s.table if s.phase == "decode" else zeros
             for s in self._slots])
        temps = np.array([s.temperature for s in self._slots],
                         np.float32)
        return tokens, pos, tables, temps

    def _decode_tick(self, decoding: list):
        tokens, pos, tables, temps = self._batch_arrays()
        t0 = time.perf_counter()
        nxt, lps, self.cache = self._decode_fn(
            self.params, self.cache, self._dev("tokens", tokens),
            self._dev("pos", pos), self._dev("tables", tables),
            self._dev("temps", temps), self._base_key,
            np.int32(self._decode_steps))
        # graftlint: disable-next-line=R001,R004 the decode tick IS the scheduler's unit of work: it must sync on the sampled tokens to route them, and the lock is held for exactly one tick by design
        nxt = np.asarray(nxt)    # device sync
        # graftlint: disable-next-line=R001,R004 same sync as nxt above — lps arrives in the same device batch, so this is a no-cost host view
        lps = np.asarray(lps)
        dt = time.perf_counter() - t0
        self._step_times.append(dt)
        self._decode_time += dt
        self._decode_steps += 1
        self._decode_tokens += len(decoding)
        self._decode_slot_steps += len(decoding)
        self._tok_window.append((dt, len(decoding)))
        for i in decoding:
            s = self._slots[i]
            s.token, s.pos = int(nxt[i]), s.pos + 1
            s.remaining -= 1
            self._emit(s, i, s.token, float(lps[i]))

    def _ngram_propose(self, s: _Slot) -> list | None:
        """Prompt-lookup proposal: find the longest n-gram (ngram_max
        down to ngram_min) whose latest earlier occurrence in the
        request's own prompt+output history matches the current suffix,
        and propose the up-to-k tokens that followed it."""
        h, n_hist = s.history, len(s.history)
        for n in range(min(self.ngram_max, n_hist - 1),
                       self.ngram_min - 1, -1):
            suf = h[-n:]
            for i in range(n_hist - n - 1, -1, -1):
                if h[i:i + n] == suf:
                    return h[i + n:i + n + self.spec_k]
        return None

    def _spec_tick(self, decoding: list):
        """One speculative device step: propose (n-gram host lookup or
        one jitted draft-model scan), verify the whole window in ONE
        batched target forward, emit `accepted + 1` tokens per slot.
        Falls back to the plain decode step when nothing is worth
        speculating on, so both paths stay compiled-exactly-once."""
        W = self.spec_window
        # Slots one token from retiring can't use speculation (and, for
        # the draft backend, retire before their stale draft cache
        # could ever be consulted again).
        worth = [i for i in decoding
                 if self._slots[i].remaining >= 2]
        proposals: dict[int, list] = {}
        tokens, pos, tables, temps = self._batch_arrays()
        t0 = time.perf_counter()
        if self.spec == "ngram":
            for i in worth:
                prop = self._ngram_propose(self._slots[i])
                if prop is not None:
                    proposals[i] = prop
            if not proposals:
                self._decode_tick(decoding)
                return
            # Junk default (repeat the current token) for rows without
            # a proposal; any accidental accepts are still exact.
            drafts = np.repeat(tokens[:, None], W - 1, axis=1)
            for i, prop in proposals.items():
                drafts[i, :] = (prop + [prop[-1]] * (W - 1))[:W - 1]
        else:
            if not worth:
                self._decode_tick(decoding)
                return
            zeros = np.zeros((self.max_blocks,), np.int32)
            dtables = np.stack(
                [s.draft_table if s.phase == "decode" else zeros
                 for s in self._slots])
            dj, self.draft_cache = self._propose_fn(
                self.draft_params, self.draft_cache,
                self._dev("tokens", tokens), self._dev("pos", pos),
                self._dev("tables", dtables), self._dev("temps", temps),
                self._base_key, np.int32(self._decode_steps))
            # graftlint: disable-next-line=R001,R004 draft proposals must reach the host to build the verify window; one sync per spec tick, same budget as the plain decode tick's
            drafts = np.asarray(dj)
            for i in worth:
                proposals[i] = drafts[i].tolist()
        window = np.concatenate([tokens[:, None], drafts], axis=1)
        out, out_lp, acc, self.cache = self._verify_fn(
            self.params, self.cache, self._dev("window", window),
            self._dev("pos", pos), self._dev("tables", tables),
            self._dev("temps", temps), self._base_key,
            np.int32(self._decode_steps))
        # graftlint: disable-next-line=R001,R004 the spec tick's one deliberate sync: accepted tokens must reach the host to emit; replaces W plain-tick syncs
        out, acc = np.asarray(out), np.asarray(acc)   # device sync
        # graftlint: disable-next-line=R001,R004 same device batch as out/acc above — already materialized, no extra round-trip
        out_lp = np.asarray(out_lp)
        dt = time.perf_counter() - t0
        self._step_times.append(dt)
        self._decode_time += dt
        self._decode_steps += 1
        self._spec_steps += 1
        self._decode_slot_steps += len(decoding)
        emitted = 0
        for i in decoding:
            s = self._slots[i]
            if i in proposals:
                self._spec_proposed += W - 1
                self._spec_accepted += int(acc[i])
            for j in range(int(acc[i]) + 1):
                if self._slots[i] is not s:
                    break   # slot retired mid-window (eos/len/budget)
                tok = int(out[i, j])
                s.token, s.pos = tok, s.pos + 1
                s.remaining -= 1
                self._decode_tokens += 1
                emitted += 1
                self._emit(s, i, tok, float(out_lp[i, j]))
        self._tok_window.append((dt, emitted))

    def run_until_idle(self):
        """Drive the scheduler until every submitted request finished."""
        while True:
            with self._lock:
                busy = self._pending or any(
                    s.active for s in self._slots)
                if not busy:
                    return
                self.step()

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def check_invariants(self):
        """Allocator/tree/slot cross-checks for the fuzz tests: every
        allocated block is accounted for by exactly its holders; an int8
        pool's scale arrays must additionally track their payload's
        block geometry exactly (one f32 scale per (position, head) row —
        refcounts need no separate audit because scales share the
        payload's block axis and ride the same copy/evict/free paths)."""
        def _audit_scales(pool, label):
            if pool is None or "k_scale" not in pool:
                return
            for nm in ("k", "v"):
                pay, sc = pool[nm], pool[nm + "_scale"]
                assert tuple(sc.shape) == tuple(pay.shape[:-1]), \
                    f"{label}{nm}_scale shape {tuple(sc.shape)} != " \
                    f"payload rows {tuple(pay.shape[:-1])}"
                assert str(sc.dtype) == "float32", \
                    f"{label}{nm}_scale dtype {sc.dtype} != float32"
                assert str(pay.dtype) == "int8", \
                    f"{label}{nm} payload dtype {pay.dtype} != int8 " \
                    f"despite scale arrays present"

        _audit_scales(self.cache, "")
        _audit_scales(self.draft_cache, "draft ")
        self._alloc.check()
        holds = collections.Counter()
        for s in self._slots:
            holds.update(s.blocks)
        if self._tree is not None:
            for nd in self._tree._nodes():
                holds.update(nd.blocks)
        for b in range(1, self._alloc.n_blocks):
            assert self._alloc.refcount(b) == holds[b], \
                f"block {b}: refcount {self._alloc.refcount(b)} != " \
                f"{holds[b]} holders"
        if self._draft_alloc is not None:
            self._draft_alloc.check()
            dholds = collections.Counter()
            for s in self._slots:
                dholds.update(s.draft_blocks)
            for b in range(1, self._draft_alloc.n_blocks):
                assert self._draft_alloc.refcount(b) == dholds[b], \
                    f"draft block {b}: refcount " \
                    f"{self._draft_alloc.refcount(b)} != {dholds[b]}"
        # Preempted-stream state: after any preempt→resume→cancel
        # interleaving, a request must live in exactly one place and
        # every output queue must still be owned by someone — a leaked
        # `_out` deque (or an errored rid still scheduled) would pin
        # consumer state forever.
        pend_rids = [q.rid for q in self._pending]
        assert len(pend_rids) == len(set(pend_rids)), \
            f"duplicate pending rids: {pend_rids}"
        slot_rids = [s.rid for s in self._slots if s.active]
        assert len(slot_rids) == len(set(slot_rids)), \
            f"duplicate slot rids: {slot_rids}"
        assert not set(pend_rids) & set(slot_rids), \
            "rid both pending and active"
        for rid in pend_rids + slot_rids:
            assert rid in self._out, f"rid {rid} has no output queue"
            assert rid not in self._done, f"rid {rid} done but scheduled"
        for rid in self._errors:
            assert rid in self._out, f"errored rid {rid} has no queue"
            assert rid not in set(pend_rids) | set(slot_rids), \
                f"errored rid {rid} still scheduled"
        # Disaggregation registries: a queued import owns a live output
        # queue and must not be scheduled anywhere else yet; a parked
        # handoff's slot/queue were already released at export, so its
        # rid must appear NOWHERE else.
        import_rids = {irid for irid, _ in self._imports}
        assert import_rids == self._import_rids, \
            f"import registry drift: {import_rids} != {self._import_rids}"
        assert not import_rids & (set(pend_rids) | set(slot_rids)), \
            "import rid also pending/active"
        for irid in import_rids:
            assert irid in self._out, f"import rid {irid} has no queue"
        handoff_rids = set(self._handoffs)
        assert not handoff_rids & (set(pend_rids) | set(slot_rids)
                                   | import_rids), \
            "handoff rid still scheduled"
        for hrid in handoff_rids:
            assert hrid not in self._out, \
                f"handoff rid {hrid} still owns an output queue"
        owners = set(pend_rids) | set(slot_rids) | self._done \
            | set(self._errors) | import_rids
        for rid in self._out:
            assert rid in owners, f"orphaned output queue for rid {rid}"
        for q in self._pending:
            assert 0 <= q.priority < self.priority_classes
            assert q.max_new_tokens >= 1, \
                f"rid {q.rid} requeued with no token budget"

    def reset_stats(self):
        """Zero the throughput/latency accounting — benches call this
        after warmup so compile time stays out of the timed region.
        NOT reset: the trace counters (`*_traces`, `swap_traces`), the
        cache itself, and `params_version` — version is identity, not a
        rate; a learner correlating trajectory tags against
        `stats()["params_version"]` must not see it rewind. The windowed
        `swaps` counter and `weight_swap_ms` DO reset."""
        with self._lock:
            self._decode_steps = 0
            self._prefill_tokens = self._decode_tokens = 0
            self._prefill_time = self._decode_time = 0.0
            self._prefill_chunks = 0
            self._prefix_hit_tokens = self._prompt_tokens = 0
            self._cow_copies = self._evicted_blocks = 0
            self._cancelled = 0
            self._max_admission_stall = 0.0
            self._step_times.clear()
            self._occupancy.clear()
            self._block_util.clear()
            self._tok_window.clear()
            self._queue_waits.clear()
            self._decode_slot_steps = 0
            self._spec_steps = 0
            self._spec_proposed = self._spec_accepted = 0
            self._swaps = 0
            self._last_swap_ms = 0.0
            self._sheds = 0
            self._watchdog_stalls = 0
            self._handoffs_exported = 0
            self._imports_completed = 0
            self._handoffs_abandoned = 0
            self._kv_blocks_exported = self._kv_blocks_imported = 0
            self._kv_export_bytes = self._kv_import_bytes = 0
            self._kv_export_ms.clear()
            self._kv_import_ms.clear()
            self._preemptions = 0
            self._reprefill_blocks = 0
            self._aging_promotions = 0
            # Zero per-class counters in place and clear wait windows —
            # the dicts themselves must survive (admitted slots index
            # into `_class_waits` by class on prefill completion).
            for cc in self._per_class.values():
                for k in cc:
                    cc[k] = 0
            for w in self._class_waits.values():
                w.clear()

    def stats(self) -> dict:
        """The engine's one stats contract — this dict feeds the serve
        autoscaler (`autoscaler.load_metrics.
        replica_demands_from_engine_stats`), `bench_infer.py`'s JSON,
        and the RL flywheel's staleness accounting. Keys:

        Scheduler/throughput:
          ``slots`` / ``active`` / ``pending`` — slot capacity, occupied
          slots, queued (unadmitted) requests.
          ``decode_steps`` — device decode/verify ticks since reset.
          ``prefill_tokens`` / ``decode_tokens`` — tokens absorbed /
          emitted since reset; ``prefill_time_s`` / ``decode_time_s``
          the device time attributed to each.
          ``prefill_chunks`` — chunked-admission device calls.
          ``slot_occupancy`` — mean fraction of slots active per tick.
          ``p50_token_latency_ms`` / ``p99_token_latency_ms`` — decode
          step-time percentiles over a 512-tick window.

        Compile-once accounting (NEVER reset — identity, not rate):
          ``prefill_traces`` / ``decode_traces`` / ``verify_traces`` /
          ``draft_traces`` / ``draft_prefill_traces`` — python traces of
          each jitted path; tests pin decode/verify to 1 per lifetime.
          ``swap_traces`` — traces of the hot-swap copy fn (once per
          distinct pytree: target and draft each trace once, ever).
          ``quantize_traces`` — traces of the int8 weight-quantize fn
          (0 for f32-weight engines; else once per distinct tree shape
          — target and quantized draft each at most once, however many
          hot-swaps re-run it).

        Paged cache:
          ``block_size`` / ``cache_blocks`` / ``blocks_in_use`` /
          ``blocks_free`` — pool geometry and live allocation.
          ``cached_prefix_blocks`` — blocks the radix tree holds.
          ``cache_block_utilization`` — mean pool utilization per tick.
          ``prefix_hit_rate`` / ``prefix_hit_tokens`` — prompt tokens
          admitted by cache reference instead of prefill.
          ``cow_copies`` — mid-block copy-on-write splits.
          ``evicted_blocks`` — blocks LRU-evicted under pressure.
          ``cancelled`` — requests cancelled/abandoned.
          ``max_admission_stall_ms`` — worst single-tick admission work
          while anything was decoding.
          ``pool_bytes`` — total device bytes of the preallocated block
          pool(s), payload plus any int8 scale arrays (draft pool
          included); fixed at construction.
          ``kv_bytes_per_token`` — main-pool bytes one cached position
          costs (all layers, K+V, scales included) — the capacity
          lever `kv_dtype="int8"` pulls (~4x down vs an f32 pool).

        Autoscaler load signals:
          ``queue_depth`` — unadmitted requests (demand ~ inflight +
          queue_depth); ``decode_tok_s`` — windowed emission rate;
          ``queue_wait_ms_p50`` / ``queue_wait_ms_p99`` — submit to
          first token.

        Telemetry (util.telemetry flight recorder + retrace sentinel):
          ``ttft_ms_p50`` / ``ttft_ms_p99`` — time-to-first-token
          percentiles, the canonical latency names over the same
          submit-to-first-token window as queue_wait_ms_* (which stay
          for the autoscaler contract).
          ``retraces_unexpected`` — traces of pinned compile-once paths
          beyond their allowance (NEVER reset; nonzero means a
          compile-once guarantee broke at runtime — each violation also
          logs one WARN).

        Speculative decoding:
          ``spec`` / ``spec_k`` — backend ('' when off) and window.
          ``spec_steps`` — verify ticks; ``acceptance_rate`` — accepted
          / proposed drafts; ``tokens_per_step`` — emitted tokens per
          decoding-slot-step (1.0 when spec is off).

        RL flywheel:
          ``params_version`` — monotonically increasing weight version;
          bumped by `update_params`, stamped on every `TokenEvent`,
          survives `reset_stats`.
          ``swaps`` — hot-swaps since reset.
          ``weight_swap_ms`` — last measured update_params-call to
          first-post-swap-token latency (0.0 until a post-swap token
          lands).

        Fault tolerance (serve-plane robustness counters):
          ``sheds`` — admissions refused with `OverloadedError` because
          the pending queue hit the `max_queue` knob or projected
          block-pool utilization crossed `shed_high_water` (both 0 when
          the knobs are off — the default).
          ``watchdog_stalls`` — scheduler ticks the watchdog thread saw
          overrun the `watchdog_s` budget (always present; 0 with the
          watchdog disabled). Each stall also logs one WARN.

        Disaggregated prefill/decode (role-specialized serving):
          ``role`` — this engine's role: ``colocated`` (default) /
          ``prefill`` (chunked prefill only, exports KV handoffs) /
          ``decode`` (colocated behavior + import target; the tag
          drives role-aware routing and per-role autoscaling).
          ``handoffs`` — prompts prefilled and exported as KV blobs
          since reset; ``imports`` — handoffs adopted into this pool.
          ``handoffs_abandoned`` — exported blobs cancelled before
          collection. ``handoffs_pending`` / ``imports_queued`` —
          blobs parked awaiting pickup / imports awaiting a slot
          (imports also count into ``queue_depth``: they are demand
          exactly like queued prompts).
          ``kv_blocks_exported`` / ``kv_blocks_imported`` — paged KV
          blocks gathered to host / scattered into this pool;
          ``kv_export_bytes`` / ``kv_import_bytes`` the host bytes
          moved (payload + int8 scale rows).
          ``kv_export_ms_p50`` / ``kv_export_ms_p99`` /
          ``kv_import_ms_p50`` / ``kv_import_ms_p99`` — per-handoff
          device->host gather / host->device scatter latency
          percentiles over a 256-handoff window.
          ``kv_gather_traces`` / ``kv_scatter_traces`` — compile-once
          counters for the block transport jits (NEVER reset; at most
          one trace per pool geometry — two with a draft pool —
          sentinel-enforced like ``decode_traces``).

        Priority / preemption (multi-tenant plane):
          ``priority_classes`` — number of configured classes (identity,
          not rate; class c+1 outranks class c).
          ``preemptions`` — active streams evicted mid-flight for a
          higher class (or a forced fault site) since reset; each one
          requeues as a chunked re-prefill and resumes token-identical.
          ``reprefill_blocks`` — KV blocks re-filled on resume that the
          radix cache did NOT cover (the true cost of preemption; 0
          when the preempt-time tree insert survives to re-admission).
          ``aging_promotions`` — starvation-guard escalations: requests
          whose queue wait exceeded the per-class aging bound and were
          admitted ahead of stride order.
          ``per_class`` — dict keyed by class id (str) with per-class
          ``submitted`` / ``completed`` / ``sheds`` / ``preemptions`` /
          ``decode_tokens`` counters plus ``pending`` / ``active``
          occupancy and ``queue_wait_ms_p50`` / ``queue_wait_ms_p99``
          over a 256-request window — the fairness/usage series the
          telemetry bridge fans out as class-tagged gauges.
        """
        with self._lock:
            self._sentinel.check()   # surface retraces since last tick
            per_class = {}
            pend_by = collections.Counter(q.priority for q in self._pending)
            act_by = collections.Counter(
                s.priority for s in self._slots if s.active)
            for c in sorted(set(self._per_class) | set(pend_by)
                            | set(act_by)):
                cw = sorted(self._class_waits.get(c, ()))

                def cpct(p, _cw=cw):
                    if not _cw:
                        return 0.0
                    return _cw[min(len(_cw) - 1,
                                   int(p / 100 * len(_cw)))] * 1e3
                per_class[str(c)] = {
                    **{k: v for k, v in self._per_class.get(c, {}).items()},
                    "pending": pend_by.get(c, 0),
                    "active": act_by.get(c, 0),
                    "queue_wait_ms_p50": cpct(50),
                    "queue_wait_ms_p99": cpct(99),
                }
            times = sorted(self._step_times)
            occ = list(self._occupancy)
            util = list(self._block_util)
            waits = sorted(self._queue_waits)
            win_t = sum(dt for dt, _ in self._tok_window)
            win_toks = sum(n for _, n in self._tok_window)

            def pct(p):
                if not times:
                    return 0.0
                return times[min(len(times) - 1,
                                 int(p / 100 * len(times)))] * 1e3

            def wpct(p):
                if not waits:
                    return 0.0
                return waits[min(len(waits) - 1,
                                 int(p / 100 * len(waits)))] * 1e3

            exp_ms = sorted(self._kv_export_ms)
            imp_ms = sorted(self._kv_import_ms)

            def xpct(p):
                if not exp_ms:
                    return 0.0
                return exp_ms[min(len(exp_ms) - 1,
                                  int(p / 100 * len(exp_ms)))]

            def ipct(p):
                if not imp_ms:
                    return 0.0
                return imp_ms[min(len(imp_ms) - 1,
                                  int(p / 100 * len(imp_ms)))]
            return {
                "slots": self.num_slots,
                "active": sum(s.active for s in self._slots),
                "pending": len(self._pending),
                "decode_steps": self._decode_steps,
                "prefill_tokens": self._prefill_tokens,
                "decode_tokens": self._decode_tokens,
                "prefill_time_s": self._prefill_time,
                "decode_time_s": self._decode_time,
                "prefill_traces": self.prefill_traces,
                "decode_traces": self.decode_traces,
                "prefill_chunks": self._prefill_chunks,
                "slot_occupancy": (sum(occ) / len(occ)) if occ else 0.0,
                "p50_token_latency_ms": pct(50),
                "p99_token_latency_ms": pct(99),
                # paged-cache accounting
                "block_size": self.block_size,
                "cache_blocks": self.cache_blocks,
                "blocks_in_use": self._alloc.used,
                "blocks_free": self._alloc.free,
                "cached_prefix_blocks": (self._tree.n_blocks()
                                         if self._tree else 0),
                "cache_block_utilization": (sum(util) / len(util)
                                            if util else 0.0),
                "prefix_hit_rate": (
                    self._prefix_hit_tokens / self._prompt_tokens
                    if self._prompt_tokens else 0.0),
                "prefix_hit_tokens": self._prefix_hit_tokens,
                "cow_copies": self._cow_copies,
                "evicted_blocks": self._evicted_blocks,
                "cancelled": self._cancelled,
                "max_admission_stall_ms": self._max_admission_stall * 1e3,
                "pool_bytes": self._pool_bytes,
                "kv_bytes_per_token": self._kv_bytes_per_token,
                # load stats the autoscaler consumes (queued imports
                # are demand exactly like queued prompts)
                "queue_depth": len(self._pending) + len(self._imports),
                "decode_tok_s": (win_toks / win_t) if win_t > 0 else 0.0,
                "queue_wait_ms_p50": wpct(50),
                "queue_wait_ms_p99": wpct(99),
                # telemetry
                "ttft_ms_p50": wpct(50),
                "ttft_ms_p99": wpct(99),
                "retraces_unexpected": self._sentinel.retraces_unexpected,
                # speculative decoding
                "spec": self.spec or "",
                "spec_k": self.spec_k if self.spec else 0,
                "verify_traces": self.verify_traces,
                "draft_traces": self.draft_traces,
                "draft_prefill_traces": self.draft_prefill_traces,
                "spec_steps": self._spec_steps,
                "acceptance_rate": (
                    self._spec_accepted / self._spec_proposed
                    if self._spec_proposed else 0.0),
                "tokens_per_step": (
                    self._decode_tokens / self._decode_slot_steps
                    if self._decode_slot_steps else 0.0),
                # RL flywheel
                "params_version": self._params_version,
                "swaps": self._swaps,
                "weight_swap_ms": self._last_swap_ms,
                "swap_traces": self.swap_traces,
                "quantize_traces": self.quantize_traces,
                # fault tolerance
                "sheds": self._sheds,
                "watchdog_stalls": self._watchdog_stalls,
                # disaggregated prefill/decode
                "role": self.role,
                "handoffs": self._handoffs_exported,
                "imports": self._imports_completed,
                "handoffs_abandoned": self._handoffs_abandoned,
                "handoffs_pending": len(self._handoffs),
                "imports_queued": len(self._imports),
                "kv_blocks_exported": self._kv_blocks_exported,
                "kv_blocks_imported": self._kv_blocks_imported,
                "kv_export_bytes": self._kv_export_bytes,
                "kv_import_bytes": self._kv_import_bytes,
                "kv_export_ms_p50": xpct(50),
                "kv_export_ms_p99": xpct(99),
                "kv_import_ms_p50": ipct(50),
                "kv_import_ms_p99": ipct(99),
                "kv_gather_traces": self.kv_gather_traces,
                "kv_scatter_traces": self.kv_scatter_traces,
                # priority / preemption
                "priority_classes": self.priority_classes,
                "preemptions": self._preemptions,
                "reprefill_blocks": self._reprefill_blocks,
                "aging_promotions": self._aging_promotions,
                "per_class": per_class,
            }


class InferenceReplica:
    """Serve deployment hosting one InferenceEngine; `__call__` returns
    a generator of token ids, which `serve.replica` automatically turns
    into a `next_chunks` stream — so `handle.stream(prompt)` yields
    tokens as they are decoded, and concurrent requests continuously
    batch into the shared engine's slots. A client that walks away
    mid-stream closes the generator, which cancels the request and
    frees its cache blocks.

    Construction takes *config kwargs*, not arrays: params are
    initialized on the replica from `seed`, so nothing heavyweight rides
    the deployment's pickled init args. Real deployments would load
    checkpointed params here instead.
    """

    def __init__(self, cfg_kwargs: dict | None = None, *,
                 slots: int = 4, max_len: int = 64, seed: int = 0,
                 engine_kwargs: dict | None = None):
        import jax
        from ray_tpu.models import gpt
        cfg = gpt.small(**(cfg_kwargs or {}))
        params = gpt.init_params(jax.random.PRNGKey(seed), cfg)
        ek = dict(engine_kwargs or {})
        # spec='draft' convenience: build the draft model here from the
        # target's config kwargs (params never ride pickled init args).
        if ek.get("spec") == "draft" and "draft_params" not in ek:
            dl = ek.pop("draft_layers", 1)
            dcfg = gpt.small(**{**(cfg_kwargs or {}), "n_layers": dl})
            ek["draft_cfg"] = dcfg
            ek["draft_params"] = gpt.init_params(
                jax.random.PRNGKey(seed + 1), dcfg)
        self.engine = InferenceEngine(
            params, cfg, slots=slots, max_len=max_len, **ek)

    def __call__(self, prompt, max_new_tokens: int = 8,
                 temperature: float = 0.0, priority: int | None = None):
        # Explicit kwarg wins; otherwise pick up the class the serve
        # path stamped on this request's context (handle/proxy), so
        # priority rides `handle.stream(prompt)` with no signature
        # changes at every hop.
        if priority is None:
            from ray_tpu.serve import priority as _prio
            priority = _prio.get_request_priority()
        rid = self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                 temperature=temperature,
                                 priority=priority)
        return self.engine.tokens_for(rid)

    def cancel(self, rid: int) -> bool:
        return self.engine.cancel(rid)

    def update_params(self, new_params, *, draft_params=None) -> int:
        """Hot-swap weights into this replica's live engine (the serve
        path the flywheel publishes through); returns the new
        params_version."""
        return self.engine.update_params(new_params,
                                         draft_params=draft_params)

    def stats(self) -> dict:
        return self.engine.stats()
