"""Request priority-class propagation for the serve path.

A request's priority class is an int in ``[0, priority_classes)`` —
higher is more important (class ``c+1`` outranks class ``c``). Clients
set it with ``handle.options(priority=2).remote(...)`` (or the
``X-Serve-Priority`` header / ``priority`` query param on the HTTP
proxy); the handle injects it as a reserved ``__serve_priority__``
kwarg, the replica pops it into a ContextVar before invoking user code,
and `InferenceReplica.__call__` reads it back when no explicit
``priority=`` kwarg was given — so the class rides the whole serve path
without threading a parameter through every hop. Deployments can set a
baseline with ``@serve.deployment(default_priority=...)``.

Inside the engine the class drives weighted-share admission ordering
(with aging so low classes never starve), class-ordered shedding, and
block-pressure preemption — see `engine.InferenceEngine`.
"""

from __future__ import annotations

import contextvars

# ContextVar, not threading.local: replica requests run as asyncio tasks
# interleaved on ONE event-loop thread, and each task carries its own
# context (the replica's sync-callable executor propagates it with
# copy_context) — same reasoning as multiplex._MODEL_ID.
_PRIORITY: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_serve_priority", default=0)


def get_request_priority() -> int:
    """The priority class of the request being handled (0 — the lowest
    class — outside a serve request or when the caller didn't set one)."""
    return _PRIORITY.get()


def _set_priority(priority: int) -> None:
    _PRIORITY.set(int(priority))
