"""Declarative Serve config: YAML/dict -> running applications.

Counterpart of the reference's `serve/schema.py`
(ServeDeploySchema/ServeApplicationSchema) + the `serve deploy`/REST
apply path (`dashboard/modules/serve/`): a config names applications by
import path and overrides per-deployment options; applying it is
idempotent reconciliation — the controller rolls replicas toward the new
spec. Schema (YAML or dict)::

    applications:
      - name: text_app
        route_prefix: /text
        import_path: my_module:app        # module attr holding a bound
                                          # Application / BoundDeployment
        deployments:                      # optional per-deployment
          - name: Summarizer              # overrides
            num_replicas: 3
            max_concurrent_queries: 16
            autoscaling_config: {min_replicas: 1, max_replicas: 5}

CLI: ``ray_tpu serve apply -f serve.yaml`` / ``ray_tpu serve status``;
REST: ``PUT /api/serve/applications`` on the dashboard with the same
body as JSON.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict


def _load_import_path(path: str):
    """'pkg.module:attr' -> the attribute (reference: common import_path
    convention in serve/schema.py)."""
    module_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {path!r} must look like 'module:attribute'")
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def build_app_from_config(app_cfg: Dict[str, Any]):
    """One application entry -> (name, Application, route_prefix)."""
    from ray_tpu.serve.api import Application, BoundDeployment

    name = app_cfg.get("name", "default")
    route_prefix = app_cfg.get("route_prefix", "/")
    app = _load_import_path(app_cfg["import_path"])
    if isinstance(app, BoundDeployment):
        app = Application(app)
    if not isinstance(app, Application):
        raise TypeError(
            f"{app_cfg['import_path']} resolved to {type(app).__name__}, "
            "expected a bound deployment / Application")

    overrides = {d["name"]: d for d in app_cfg.get("deployments", [])}
    if overrides:
        known = {}
        for node in app._collect():
            known[node.name] = node
        unknown = set(overrides) - set(known)
        if unknown:
            raise ValueError(
                f"config overrides unknown deployments {sorted(unknown)} "
                f"(app has {sorted(known)})")
        for dep_name, od in overrides.items():
            node = known[dep_name]
            opts = {k: v for k, v in od.items() if k != "name"}
            node.deployment = node.deployment.options(**opts)
    return name, app, route_prefix


def apply_config(config) -> Dict[str, str]:
    """Apply a declarative config (dict, YAML string, or path to a YAML
    file). Returns {app_name: "deployed"}. Idempotent: re-applying rolls
    deployments toward the new spec (controller reconciliation)."""
    import os

    from ray_tpu.serve import api

    if isinstance(config, str):
        import yaml
        if os.path.exists(config):
            with open(config) as f:
                config = yaml.safe_load(f)
        else:
            config = yaml.safe_load(config)
    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("config must carry an 'applications' list")

    out = {}
    for app_cfg in config["applications"]:
        name, app, route_prefix = build_app_from_config(app_cfg)
        api.run(app, name=name, route_prefix=route_prefix)
        out[name] = "deployed"
    return out
