"""@serve.batch — transparent request batching.

Counterpart of the reference's `serve/batching.py` (`@serve.batch`): calls
from concurrent requests accumulate until max_batch_size or
batch_wait_timeout_s, then the wrapped function runs once on the list of
inputs and each caller gets its element back. This is the TPU
batch-inference hot path — the MXU wants batched matmuls, so the batcher
is what turns request-at-a-time serving into device-shaped work.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._queue: queue.Queue = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="serve-batcher")
                self._thread.start()

    def _loop(self):
        while True:
            first = self._queue.get()
            batch = [first]
            # Accumulate until size or timeout, BLOCKING on the remaining
            # deadline each wait (the old loop spun on get(timeout=1ms),
            # burning a core and adding up to 1 ms of jitter per item).
            # A full batch falls out of the size check immediately; a
            # timed-out get ends the window without a timer thread.
            deadline = time.monotonic() + self.timeout
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            inputs = [item[0] for item in batch]
            events = [item[1] for item in batch]
            results = [item[2] for item in batch]
            try:
                outs = self.fn(inputs)
                if len(outs) != len(inputs):
                    raise ValueError(
                        f"@serve.batch function returned {len(outs)} "
                        f"results for {len(inputs)} inputs")
                for slot, out, ev in zip(results, outs, events):
                    slot.append(out)
                    ev.set()
            except Exception as e:
                for slot, ev in zip(results, events):
                    slot.append(e)
                    slot.append(None)     # marker: error in slot[0]
                    ev.set()

    def submit(self, item):
        self._ensure_thread()
        ev = threading.Event()
        slot: List = []
        self._queue.put((item, ev, slot))
        ev.wait()
        if len(slot) == 2:                # error marker
            raise slot[0]
        return slot[0]


# Batchers hold threads/locks and therefore must NOT be captured in the
# decorated wrapper's closure or referenced-globals set: deployments are
# cloudpickled to replicas, and cloudpickle serializes __main__-module
# wrappers BY VALUE together with every module global they name. All
# state lives behind _dispatch (an importable global, pickled by
# reference); batchers are created lazily per process.
import weakref

_FN_BATCHERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_FN_LOCK = threading.Lock()


def _dispatch(f, wrapper, cfg, args):
    max_batch_size, batch_wait_timeout_s = cfg
    if len(args) == 2:          # bound method: (self, item)
        owner, item = args
        attr = f"_serve_batcher_{f.__name__}"   # one batcher PER method
        b = owner.__dict__.get(attr)
        if b is None:
            b = _Batcher(functools.partial(f, owner),
                         max_batch_size, batch_wait_timeout_s)
            setattr(owner, attr, b)
        return b.submit(item)
    (item,) = args              # plain function
    with _FN_LOCK:
        b = _FN_BATCHERS.get(wrapper)
        if b is None:
            b = _Batcher(f, max_batch_size, batch_wait_timeout_s)
            _FN_BATCHERS[wrapper] = b
    return b.submit(item)


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: `fn(list_of_inputs) -> list_of_outputs` is called on
    accumulated batches; each caller passes/receives a single element."""

    def wrap(f):
        cfg = (max_batch_size, batch_wait_timeout_s)

        @functools.wraps(f)
        def inner(*args):
            return _dispatch(f, inner, cfg, args)

        return inner

    if fn is not None:
        return wrap(fn)
    return wrap
