"""Replica actor — hosts one copy of the user's deployment callable.

Counterpart of the reference's `RayServeReplica`
(`serve/_private/replica.py:429`, handle_request :695): wraps the user
class/function, counts in-flight requests for autoscaling, and exposes
health checks. Runs with max_concurrency > 1 so a slow request doesn't
serialize the replica (the reference uses asyncio; our actor runtime uses
a thread pool, worker_main.py max_concurrency).
"""

from __future__ import annotations

import threading
import time


class Replica:
    def __init__(self, serialized_init: dict):
        """serialized_init: {"callable": cls_or_fn, "init_args": tuple,
        "init_kwargs": dict, "deployment_name": str}"""
        self.deployment_name = serialized_init["deployment_name"]
        target = serialized_init["callable"]
        args = serialized_init.get("init_args", ())
        kwargs = serialized_init.get("init_kwargs", {})
        if isinstance(target, type):
            self.callable = target(*args, **kwargs)
            self._is_function = False
        else:
            self.callable = target
            self._is_function = True
        self._inflight = 0
        self._total = 0
        self._lock = threading.Lock()
        self._started = time.time()

    def ready(self) -> bool:
        return True

    def check_health(self) -> bool:
        """Reference: user-defined check_health on the deployment class
        (deployment_state.py health checks)."""
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def _enter(self):
        with self._lock:
            self._inflight += 1
            self._total += 1

    def _exit(self):
        with self._lock:
            self._inflight -= 1

    def handle_request(self, args: tuple, kwargs: dict):
        """__call__ path (HTTP and plain handle calls)."""
        self._enter()
        try:
            target = (self.callable if self._is_function
                      else self.callable.__call__)
            return target(*args, **kwargs)
        finally:
            self._exit()

    def handle_method(self, method: str, args: tuple, kwargs: dict):
        """handle.method.remote path (model composition)."""
        self._enter()
        try:
            return getattr(self.callable, method)(*args, **kwargs)
        finally:
            self._exit()

    def stats(self) -> dict:
        """Autoscaling signal (reference: autoscaling_metrics.py pulls
        per-replica queue lengths)."""
        with self._lock:
            return {"inflight": self._inflight, "total": self._total,
                    "uptime_s": time.time() - self._started}

    def prepare_shutdown(self) -> bool:
        """Graceful-teardown hook called by the controller before kill:
        runs the user's __del__ (resource release) while the process is
        still healthy (reference: replica graceful_shutdown,
        deployment_state.py)."""
        fn = getattr(self.callable, "__del__", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                pass
        return True
