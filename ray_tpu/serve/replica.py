"""Replica actor — hosts one copy of the user's deployment callable.

Counterpart of the reference's `RayServeReplica`
(`serve/_private/replica.py:429`, handle_request :695): wraps the user
class/function, counts in-flight requests for autoscaling, and exposes
health checks. Async end-to-end: handle_request/handle_method are
coroutines, so the replica runs as an asyncio actor (one event loop,
max_concurrency as a semaphore — worker_main.py) and thousands of
concurrent slow requests overlap on awaits; sync user callables execute
on a worker thread so they can't stall the loop.
"""

from __future__ import annotations

import itertools
import threading
import time
from types import GeneratorType

STREAM_MARKER = "__serve_stream__"
from ray_tpu._private.constants import (
    SERVE_STREAM_BATCH as _STREAM_BATCH,
    SERVE_STREAM_IDLE_TTL_S as _STREAM_IDLE_TTL_S,
)


class StreamingResponse:
    """Deployment return type for streamed HTTP bodies (reference:
    serve's StreamingResponse over `replica.py:249` generator replies).
    Wraps any iterable of bytes/str chunks."""

    def __init__(self, content, content_type: str = "text/plain",
                 status: int = 200):
        self.content = content
        self.content_type = content_type
        self.status = status


class Replica:
    # Control-plane RPCs skip the actor's max_concurrency semaphore
    # (worker_main._run_task_async; reference: Ray's concurrency
    # groups): a replica whose whole admission window is parked in
    # long-blocking next_chunks pulls must still answer the
    # controller's stats scrape and health ping promptly — starving
    # them reads as dead replicas and invisible queue depth.
    _control_plane_methods = ("stats", "check_health", "ready",
                              "install_faults", "prepare_shutdown",
                              "cancel_stream")

    def __init__(self, serialized_init: dict):
        """serialized_init: {"callable": cls_or_fn, "init_args": tuple,
        "init_kwargs": dict, "deployment_name": str}"""
        self.deployment_name = serialized_init["deployment_name"]
        # Priority class stamped on requests that carry none of their
        # own (@serve.deployment(default_priority=...)).
        self._default_priority = int(
            serialized_init.get("default_priority", 0))
        target = serialized_init["callable"]
        args = serialized_init.get("init_args", ())
        kwargs = serialized_init.get("init_kwargs", {})
        if isinstance(target, type):
            self.callable = target(*args, **kwargs)
            self._is_function = False
        else:
            self.callable = target
            self._is_function = True
        self._inflight = 0
        self._total = 0
        self._lock = threading.Lock()
        self._started = time.time()
        # stream_id -> [iterator, last_access_ts]; idle entries are reaped
        # (a caller that got the marker but never drains would otherwise
        # pin the generator + its closure for the replica's lifetime)
        self._streams: dict[int, list] = {}
        self._stream_ids = itertools.count(1)
        # Sync handlers get a dedicated pool sized to the concurrency the
        # deployment declared: the default asyncio executor caps at
        # ~min(32, cpus+4) threads, which would throttle sync-handler
        # concurrency below max_concurrent_queries and can deadlock a
        # deployment whose sync handlers call back into itself.
        from concurrent.futures import ThreadPoolExecutor
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(
                serialized_init.get("max_concurrent_queries", 8))),
            thread_name_prefix=f"replica-{self.deployment_name}")
        # Telemetry bridge: this replica's stats() (its own counters
        # merged over the user callable's — engine stats for
        # InferenceReplica deployments) become replica_* series on
        # /metrics, tagged by a per-replica source id. Worker-resident
        # replicas reach the driver scrape via the metrics flusher.
        from ray_tpu.util import telemetry as _telemetry
        self._telemetry_name = _telemetry.register_stats_source(
            _telemetry.next_name(f"replica:{self.deployment_name}#"),
            self, kind="replica")

    def ready(self) -> bool:
        return True

    def check_health(self) -> bool:
        """Reference: user-defined check_health on the deployment class
        (deployment_state.py health checks)."""
        from ray_tpu.util import faults
        # fault site: 'fail' = a missed ping (controller strikes it),
        # 'kill' = the replica dies during the ping (a flap)
        faults.check("replica.health_ping")
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def install_faults(self, plan) -> bool:
        """Install a `util.faults.FaultPlan` in THIS replica's process —
        the chaos tests' lever for killing/failing one specific replica
        at a deterministic point. Pass None to clear."""
        from ray_tpu.util import faults
        if plan is None:
            faults.clear()
        else:
            faults.install(plan)
        return True

    def _enter(self):
        with self._lock:
            self._inflight += 1
            self._total += 1

    def _exit(self):
        with self._lock:
            self._inflight -= 1

    def _maybe_stream(self, result):
        """Generator / StreamingResponse results stay ON the replica; the
        caller gets a marker and drains chunk batches via next_chunks
        (reference: streaming replies, replica.py:249 — a generator can't
        ride the object store)."""
        if isinstance(result, StreamingResponse):
            return {STREAM_MARKER: self._register_stream(
                        iter(result.content)),
                    "content_type": result.content_type,
                    "status": result.status}
        if isinstance(result, GeneratorType):
            return {STREAM_MARKER: self._register_stream(result),
                    "content_type": "application/octet-stream",
                    "status": 200}
        return result

    def _register_stream(self, it) -> int:
        sid = next(self._stream_ids)
        now = time.time()
        with self._lock:
            stale = [s for s, (_, ts) in self._streams.items()
                     if now - ts > _STREAM_IDLE_TTL_S]
            for s in stale:
                dead, _ = self._streams.pop(s)
                if hasattr(dead, "close"):
                    try:
                        dead.close()
                    except Exception:
                        pass
            self._streams[sid] = [it, now]
        return sid

    @staticmethod
    def _pop_model_id(kwargs: dict) -> str:
        return kwargs.pop("__multiplexed_model_id__", "")

    def _pop_priority(self, kwargs: dict) -> int:
        return int(kwargs.pop("__serve_priority__",
                              self._default_priority))

    async def _invoke(self, target, args, kwargs):
        """Run the user callable without stalling the replica: coroutine
        functions are awaited on the replica's event loop; sync callables
        leave the loop for a worker thread (carrying the request context,
        so get_multiplexed_model_id still resolves there)."""
        import asyncio
        import contextvars
        import inspect
        result = None
        if inspect.iscoroutinefunction(target):
            result = await target(*args, **kwargs)
        else:
            ctx = contextvars.copy_context()
            loop = asyncio.get_running_loop()
            result = await loop.run_in_executor(
                self._executor, lambda: ctx.run(target, *args, **kwargs))
        if inspect.isawaitable(result):   # sync fn returning a coroutine
            result = await result
        return result

    async def handle_request(self, args: tuple, kwargs: dict):
        """__call__ path (HTTP and plain handle calls). Async end-to-end
        (reference: `serve/_private/replica.py:429` — the replica IS an
        asyncio actor; thousands of slow requests overlap on awaits)."""
        from ray_tpu.serve.multiplex import _set_model_id
        from ray_tpu.serve.priority import _set_priority
        kwargs = dict(kwargs)
        _set_model_id(self._pop_model_id(kwargs))
        _set_priority(self._pop_priority(kwargs))
        self._enter()
        try:
            target = (self.callable if self._is_function
                      else self.callable.__call__)
            return self._maybe_stream(
                await self._invoke(target, args, kwargs))
        finally:
            self._exit()

    async def handle_method(self, method: str, args: tuple, kwargs: dict):
        """handle.method.remote path (model composition)."""
        from ray_tpu.serve.multiplex import _set_model_id
        from ray_tpu.serve.priority import _set_priority
        kwargs = dict(kwargs)
        _set_model_id(self._pop_model_id(kwargs))
        _set_priority(self._pop_priority(kwargs))
        self._enter()
        try:
            return self._maybe_stream(await self._invoke(
                getattr(self.callable, method), args, kwargs))
        finally:
            self._exit()

    async def next_chunks(self, stream_id: int,
                          max_chunks: int = _STREAM_BATCH):
        """Pull the next batch of chunks from a registered stream.
        Returns (chunks, done); the stream is dropped when done. An
        unknown/TTL-reaped id returns (None, True) — consumers must treat
        that as an ERROR, not a clean EOF, or a reaped stream looks like
        a complete (truncated) response. Async wrapper: the user's
        generator may block per chunk (inference, I/O), which must not
        stall the replica's event loop."""
        import asyncio
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._next_chunks_sync, stream_id, max_chunks)

    def _next_chunks_sync(self, stream_id: int, max_chunks: int):
        with self._lock:
            entry = self._streams.get(stream_id)
            if entry is not None:
                entry[1] = time.time()
        if entry is None:
            return None, True
        it = entry[0]
        chunks = []
        done = False
        try:
            for _ in range(max_chunks):
                chunks.append(next(it))
        except StopIteration:
            done = True
        if done:
            with self._lock:
                self._streams.pop(stream_id, None)
        return chunks, done

    def cancel_stream(self, stream_id: int) -> bool:
        with self._lock:
            entry = self._streams.pop(stream_id, None)
        if entry is not None and hasattr(entry[0], "close"):
            try:
                entry[0].close()
            except Exception:
                pass
        return entry is not None

    def stats(self) -> dict:
        """Autoscaling signal (reference: autoscaling_metrics.py pulls
        per-replica queue lengths). If the user callable exposes its own
        `stats()` (e.g. `InferenceReplica` surfacing the engine's
        `queue_depth` / `decode_tok_s` / queue-wait percentiles), those
        fields are merged in — the replica-level counters win on
        collision. `streams` counts still-registered response streams,
        which the controller's scale-down drain waits on alongside
        `inflight` (and which the stream-leak regression test pins to 0
        after handles abandon/time out). Engine-backed deployments also
        merge the fault-tolerance counters (``sheds``,
        ``watchdog_stalls`` — see `InferenceEngine.stats`), which the
        telemetry bridge republishes as `replica_*` series."""
        with self._lock:
            out = {"inflight": self._inflight, "total": self._total,
                   "streams": len(self._streams),
                   "uptime_s": time.time() - self._started}
        fn = getattr(self.callable, "stats", None)
        if callable(fn) and not self._is_function:
            try:
                user = fn()
                if isinstance(user, dict):
                    for k, v in user.items():
                        out.setdefault(k, v)
            except Exception:
                pass
        return out

    def prepare_shutdown(self) -> bool:
        """Graceful-teardown hook called by the controller before kill:
        runs the user's __del__ (resource release) while the process is
        still healthy (reference: replica graceful_shutdown,
        deployment_state.py)."""
        fn = getattr(self.callable, "__del__", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                pass
        return True
