"""Model multiplexing: many models per deployment, LRU-cached per replica.

Counterpart of the reference's `serve/multiplex.py`
(`@serve.multiplexed` + `serve.get_multiplexed_model_id`): one
deployment serves N models; each replica lazily loads the models routed
to it and keeps at most `max_num_models_per_replica` resident (LRU).
Requests carry a model id via
``handle.options(multiplexed_model_id="m1").remote(...)``; the handle
routes a given model id to a stable replica (rendezvous hashing), so a
model's cache hits keep landing where it's already loaded.
"""

from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from functools import wraps

# ContextVar, not threading.local: replica requests run as asyncio tasks
# interleaved on ONE event-loop thread, and each task carries its own
# context (the replica's sync-callable executor propagates it with
# copy_context)
_MODEL_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request (reference:
    serve.get_multiplexed_model_id)."""
    return _MODEL_ID.get()


def _set_model_id(value: str):
    _MODEL_ID.set(value)


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    """Decorator for the replica method that loads a model by id::

        @serve.deployment
        class ModelServer:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_model(model_id)      # expensive

            def __call__(self, x):
                model = self.get_model(serve.get_multiplexed_model_id())
                return model(x)

    The wrapped method becomes an LRU cache keyed by model id, scoped to
    the replica instance; evicted models with a ``__del__``/``close`` are
    released to the GC.
    """

    def wrap(f):
        @wraps(f)
        def cached(self, model_id: str):
            cache = getattr(self, "_mux_cache", None)
            if cache is None:
                cache = self._mux_cache = OrderedDict()
                self._mux_lock = threading.Lock()
            with self._mux_lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
            model = f(self, model_id)
            with self._mux_lock:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    _evicted_id, evicted = cache.popitem(last=False)
                    close = getattr(evicted, "close", None)
                    if callable(close):
                        try:
                            close()
                        except Exception:
                            pass
            return model

        cached.__ray_tpu_multiplexed__ = True
        return cached

    if func is not None:
        return wrap(func)
    return wrap
