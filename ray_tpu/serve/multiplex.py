"""Model multiplexing: many models per deployment, LRU-cached per replica.

Counterpart of the reference's `serve/multiplex.py`
(`@serve.multiplexed` + `serve.get_multiplexed_model_id`): one
deployment serves N models; each replica lazily loads the models routed
to it and keeps at most `max_num_models_per_replica` resident (LRU).
Requests carry a model id via
``handle.options(multiplexed_model_id="m1").remote(...)``; the handle
routes a given model id to a stable replica (rendezvous hashing), so a
model's cache hits keep landing where it's already loaded.
"""

from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from functools import wraps

# ContextVar, not threading.local: replica requests run as asyncio tasks
# interleaved on ONE event-loop thread, and each task carries its own
# context (the replica's sync-callable executor propagates it with
# copy_context)
_MODEL_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_multiplexed_model_id", default="")

# Guards lazy creation of per-instance multiplex state (two first-ever
# requests racing the `_mux_cache is None` check would otherwise each
# build a cache and one set of loads would be orphaned).
_MUX_INIT_LOCK = threading.Lock()


def _mux_get(f, max_models: int, self, model_id: str):
    """Body of the multiplexed wrapper. Lives at module level so the
    decorated method's closure/referenced-globals stay free of lock
    objects: deployments are cloudpickled to replicas, and cloudpickle
    serializes test-/__main__-module classes BY VALUE together with
    every global their methods name (same rule as batching._dispatch) —
    a captured threading.Lock would make the deployment unpicklable."""
    # Lazy per-instance state; _mux_lock is assigned LAST so any thread
    # that sees it also sees the cache/in-flight dicts.
    if getattr(self, "_mux_lock", None) is None:
        with _MUX_INIT_LOCK:
            if getattr(self, "_mux_lock", None) is None:
                self._mux_cache = OrderedDict()
                self._mux_inflight = {}
                self._mux_lock = threading.Lock()
    # The load runs OUTSIDE the lock (it is the expensive part), but a
    # per-key in-flight event makes exactly one caller the loader; the
    # rest wait on the event and re-check the cache. Without it,
    # concurrent misses for the same id each loaded the model, and
    # eviction could close() a copy still in use by a loser.
    while True:
        with self._mux_lock:
            cache = self._mux_cache
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            event = self._mux_inflight.get(model_id)
            if event is None:
                event = threading.Event()
                self._mux_inflight[model_id] = event
                loading = True
            else:
                loading = False
        if not loading:
            event.wait()
            continue   # loaded (hit) or failed (become loader)
        try:
            model = f(self, model_id)
        except BaseException:
            with self._mux_lock:
                self._mux_inflight.pop(model_id, None)
            event.set()   # wake waiters; one of them retries
            raise
        with self._mux_lock:
            cache[model_id] = model
            cache.move_to_end(model_id)
            self._mux_inflight.pop(model_id, None)
            while len(cache) > max_models:
                _evicted_id, evicted = cache.popitem(last=False)
                close = getattr(evicted, "close", None)
                if callable(close):
                    try:
                        close()
                    except Exception:
                        pass
        event.set()
        return model


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request (reference:
    serve.get_multiplexed_model_id)."""
    return _MODEL_ID.get()


def _set_model_id(value: str):
    _MODEL_ID.set(value)


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    """Decorator for the replica method that loads a model by id::

        @serve.deployment
        class ModelServer:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_model(model_id)      # expensive

            def __call__(self, x):
                model = self.get_model(serve.get_multiplexed_model_id())
                return model(x)

    The wrapped method becomes an LRU cache keyed by model id, scoped to
    the replica instance; evicted models with a ``__del__``/``close`` are
    released to the GC.
    """

    def wrap(f):
        @wraps(f)
        def cached(self, model_id: str):
            return _mux_get(f, max_num_models_per_replica, self,
                            model_id)

        cached.__ray_tpu_multiplexed__ = True
        return cached

    if func is not None:
        return wrap(func)
    return wrap
