"""Disaggregated prefill/decode serving: role-specialized replicas and
the KV-block stream between them.

The paged engine already splits a request's life into two phases with
opposite resource shapes — chunked prefill (compute-bound, bursty) and
decode (bandwidth-bound, steady). Colocating them means a burst of long
prompts steals decode ticks from every active stream. This module splits
the fleet instead (the architecture of DistServe/Splitwise and the
reference's prefill-disaggregation work):

- `PrefillReplica` runs an `InferenceEngine(role="prefill")`: chunked
  prefill only, prompt-only block footprint. A request returns a small
  *handoff descriptor*; the finished KV blocks (payload + any int8 scale
  rows, block-aligned) stay parked on the replica until the decode side
  pulls them over netaddr.
- `DecodeReplica` runs `role="decode"`: it dials the prefill replica's
  block server, reassembles the blob (header rides a coalesced
  `BatchedConnection` frame, each array travels as one zero-pickle raw
  frame), imports it into its own pool via `engine.import_handoff`, and
  serves the token stream — greedy token-identical to a colocated run,
  picking up at the first generated token the prefill engine already
  sampled.
- `DisaggHandle` pairs the two deployment handles: prompt → prefill pool
  (retrying `call`, so a prefill replica death mid-handoff fails over
  through the PR-12 path), resume → decode pool (`stream`, with a
  disagg-aware failover policy that re-prefills prompt+emitted on a
  fresh decode replica if the decode side dies mid-stream).

Wire format per pulled handoff (one logical exchange per request):
  -> {"handoff_id": rid}                      (pickled, batched frame)
  <- header: blob metadata + frame manifest   (pickled, batched frame)
  <- one raw byte frame per payload array, manifest order
`BatchedConnection.send_bytes` flushes pending logical messages before
the raw write under the same wire-lock hold, so header/payload adjacency
is guaranteed without an explicit barrier.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np


def _np_frames(blob: dict):
    """Split a handoff blob into (meta, manifest, arrays): every numpy
    array in the payload (and draft payload) becomes one raw wire frame,
    described by a manifest entry the receiver rebuilds from."""
    meta = {k: v for k, v in blob.items()
            if k not in ("payload", "draft_payload", "prompt")}
    meta["prompt"] = [int(t) for t in np.asarray(blob["prompt"]).ravel()]
    meta["has_draft"] = blob.get("draft_payload") is not None
    manifest, arrays = [], []
    for which in ("payload", "draft_payload"):
        blocks = blob.get(which) or []
        for i, blk in enumerate(blocks):
            for name in sorted(blk):
                arr = np.ascontiguousarray(blk[name])
                manifest.append((which, i, name, arr.shape,
                                 str(arr.dtype)))
                arrays.append(arr)
    meta["manifest"] = manifest
    return meta, manifest, arrays


def _blob_from_frames(meta: dict, frames: list) -> dict:
    """Inverse of `_np_frames`: reassemble the engine-shaped blob from
    the header and the received raw byte frames."""
    payload: dict[int, dict] = {}
    draft: dict[int, dict] = {}
    for (which, i, name, shape, dtype), buf in zip(meta["manifest"],
                                                   frames):
        arr = np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)
        (payload if which == "payload" else draft).setdefault(
            i, {})[name] = arr
    blob = {k: v for k, v in meta.items()
            if k not in ("manifest", "has_draft")}
    blob["payload"] = [payload[i] for i in sorted(payload)]
    blob["draft_payload"] = ([draft[i] for i in sorted(draft)]
                             if meta["has_draft"] else None)
    return blob


class PrefillReplica:
    """Serve deployment hosting a prefill-role engine plus the netaddr
    block server the decode side pulls finished KV from.

    `__call__` runs the chunked prefill to completion and returns a
    handoff *descriptor* — small enough to ride the control plane — with
    the dial address of this replica's block server. The heavyweight KV
    blob itself never touches the object store: it stays parked here
    until exactly one decode replica streams it out (or the park TTL
    reaps it, so an abandoned descriptor can't pin host memory forever).
    """

    _PARK_TTL_S = 120.0

    def __init__(self, cfg_kwargs: dict | None = None, *,
                 slots: int = 4, max_len: int = 64, seed: int = 0,
                 engine_kwargs: dict | None = None):
        from ray_tpu.serve.engine import InferenceReplica
        inner = InferenceReplica(cfg_kwargs, slots=slots, max_len=max_len,
                                 seed=seed,
                                 engine_kwargs={**(engine_kwargs or {}),
                                                "role": "prefill"})
        self.engine = inner.engine
        self._lock = threading.Lock()
        self._parked: dict[int, tuple[dict, float]] = {}
        self._authkey = os.urandom(16)
        from ray_tpu._private import netaddr
        self._listener = netaddr.listener(("0.0.0.0", 0), self._authkey)
        self._addr = netaddr.bound_address(self._listener)
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="disagg-kv-server")
        self._accept_thread.start()

    # -- request path -----------------------------------------------------

    def __call__(self, prompt, max_new_tokens: int = 8,
                 temperature: float = 0.0, priority: int | None = None):
        if priority is None:
            from ray_tpu.serve import priority as _prio
            priority = _prio.get_request_priority()
        rid = self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                 temperature=temperature,
                                 priority=priority)
        blob = self.engine.handoff_for(rid)
        now = time.time()
        with self._lock:
            stale = [r for r, (_, ts) in self._parked.items()
                     if now - ts > self._PARK_TTL_S]
            for r in stale:
                self._parked.pop(r, None)
            self._parked[rid] = (blob, now)
        return {
            "handoff_addr": self._addr,
            "handoff_key": self._authkey.hex(),
            "handoff_id": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "priority": int(priority),
            "kv_bytes": int(blob["kv_bytes"]),
        }

    # -- block server -----------------------------------------------------

    def _accept_loop(self):
        while not self._closing:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="disagg-kv-conn").start()

    def _serve_conn(self, conn):
        """One puller connection: any number of handoff pulls, then EOF.
        Each pull pops the blob — a handoff streams out exactly once."""
        try:
            while True:
                req = conn.recv()
                rid = int(req["handoff_id"])
                with self._lock:
                    entry = self._parked.pop(rid, None)
                if entry is None:
                    conn.send({"error": f"unknown handoff {rid} "
                                        "(expired or already pulled)"})
                    continue
                meta, _, arrays = _np_frames(entry[0])
                conn.send(meta)
                for arr in arrays:
                    # tobytes(): dtypes like bfloat16 have no buffer
                    # protocol, so the ndarray itself can't go on the wire.
                    conn.send_bytes(arr.tobytes())
        except (EOFError, OSError, ConnectionError):
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass

    # -- control surface --------------------------------------------------

    def cancel(self, rid: int) -> bool:
        with self._lock:
            parked = self._parked.pop(rid, None) is not None
        return self.engine.cancel(rid) or parked

    def update_params(self, new_params, *, draft_params=None) -> int:
        return self.engine.update_params(new_params,
                                         draft_params=draft_params)

    def stats(self) -> dict:
        out = self.engine.stats()
        with self._lock:
            out["handoffs_parked"] = len(self._parked)
        return out

    def __del__(self):
        self._closing = True
        try:
            self._listener.close()
        except Exception:
            pass


class DecodeReplica:
    """Serve deployment hosting a decode-role engine: resumes handoff
    descriptors by streaming the KV blob from the prefill replica's
    block server and importing it into the local pool; also accepts a
    plain prompt (full local prefill) — the failover resubmission path
    and the shape `token_resume` can rebuild.
    """

    def __init__(self, cfg_kwargs: dict | None = None, *,
                 slots: int = 4, max_len: int = 64, seed: int = 0,
                 engine_kwargs: dict | None = None):
        from ray_tpu.serve.engine import InferenceReplica
        inner = InferenceReplica(cfg_kwargs, slots=slots, max_len=max_len,
                                 seed=seed,
                                 engine_kwargs={**(engine_kwargs or {}),
                                                "role": "decode"})
        self.engine = inner.engine
        self._lock = threading.Lock()
        # serializes whole pull exchanges (send..recv_bytes*) — a
        # blocking wire wait must never run under self._lock, which the
        # controller's stats scrape needs promptly
        self._pull_mu = threading.Lock()
        # (addr, key) -> BatchedConnection, reused across pulls so the
        # PR-17 frame coalescing actually amortizes
        self._conns: dict = {}
        self._pull_ms: list = []
        self._handoff_fallbacks = 0
        self._kv_pulled_bytes = 0
        self._kv_pull_s = 0.0

    def _conn_for(self, addr: str, key: bytes):
        from ray_tpu._private import netaddr
        with self._lock:
            conn = self._conns.get((addr, key))
        if conn is not None and not conn.closed:
            return conn
        conn = netaddr.client(addr, key)
        with self._lock:
            self._conns[(addr, key)] = conn
        return conn

    def _pull_blob(self, desc: dict) -> dict:
        addr = desc["handoff_addr"]
        key = bytes.fromhex(desc["handoff_key"])
        conn = self._conn_for(addr, key)
        # one pull exchange at a time per replica: request/response pairs
        # must not interleave on the shared connection
        with self._pull_mu:
            conn.send({"handoff_id": desc["handoff_id"]})
            conn.flush()
            meta = conn.recv()
            if "error" in meta:
                raise KeyError(meta["error"])
            frames = [conn.recv_bytes() for _ in meta["manifest"]]
        return _blob_from_frames(meta, frames)

    def __call__(self, request, max_new_tokens: int = 8,
                 temperature: float = 0.0, priority: int | None = None):
        if isinstance(request, dict) and "handoff_addr" in request:
            return self.resume_from(request)
        if priority is None:
            from ray_tpu.serve import priority as _prio
            priority = _prio.get_request_priority()
        rid = self.engine.submit(request, max_new_tokens=max_new_tokens,
                                 temperature=temperature,
                                 priority=priority)
        return self.engine.tokens_for(rid)

    def resume_from(self, desc: dict):
        """Pull the descriptor's KV blob, import it, and return the
        token generator continuing at the first generated token. If the
        prefill replica died (or the blob expired) between descriptor
        and pull, fall back to a full local prefill of the descriptor's
        prompt — greedy decode makes that token-identical, just without
        the transfer savings."""
        t0 = time.perf_counter()
        try:
            blob = self._pull_blob(desc)
        except (KeyError, OSError, EOFError, ConnectionError):
            with self._lock:
                self._handoff_fallbacks += 1
                self._conns.pop((desc["handoff_addr"],
                                 bytes.fromhex(desc["handoff_key"])),
                                None)
            rid = self.engine.submit(
                desc["prompt"],
                max_new_tokens=int(desc["max_new_tokens"]),
                temperature=float(desc["temperature"]),
                priority=int(desc.get("priority", 0)))
            return self.engine.tokens_for(rid)
        rid = self.engine.import_handoff(blob)
        dt = time.perf_counter() - t0
        with self._lock:
            self._pull_ms.append(dt * 1e3)
            del self._pull_ms[:-256]
            self._kv_pulled_bytes += int(blob.get("kv_bytes", 0))
            self._kv_pull_s += dt
        self.engine._recorder.on_handoff(rid, dt)
        return self.engine.tokens_for(rid)

    def cancel(self, rid: int) -> bool:
        return self.engine.cancel(rid)

    def update_params(self, new_params, *, draft_params=None) -> int:
        return self.engine.update_params(new_params,
                                         draft_params=draft_params)

    def stats(self) -> dict:
        out = self.engine.stats()
        with self._lock:
            pulls = sorted(self._pull_ms)
            out["handoff_fallbacks"] = self._handoff_fallbacks
            out["kv_pulled_bytes"] = self._kv_pulled_bytes
            out["kv_transfer_gbps"] = (
                self._kv_pulled_bytes / max(self._kv_pull_s, 1e-9) / 1e9)
            out["handoff_pull_ms_p50"] = (
                pulls[len(pulls) // 2] if pulls else 0.0)
            out["handoff_pull_ms_p99"] = (
                pulls[min(len(pulls) - 1, int(len(pulls) * 0.99))]
                if pulls else 0.0)
        return out

    def __del__(self):
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            try:
                c.close()
            except Exception:
                pass


def disagg_resume(args, kwargs, emitted):
    """`DisaggHandle.stream` failover policy for the DECODE leg: the
    original submission was a handoff descriptor, so rebuild a plain
    prompt+emitted resubmission from the prompt the descriptor carries —
    a fresh decode replica re-prefills locally and the spliced stream
    stays token-identical under greedy decode. Returns None when the
    token budget is already spent (stream was complete at death)."""
    desc = args[0]
    if not (isinstance(desc, dict) and "prompt" in desc):
        raise TypeError("disagg_resume needs a handoff descriptor")
    remaining = int(desc["max_new_tokens"]) - len(emitted)
    if remaining <= 0:
        return None
    prompt = list(desc["prompt"]) + [int(t) for t in emitted]
    return (prompt,), {"max_new_tokens": remaining,
                       "temperature": float(desc["temperature"]),
                       "priority": int(desc.get("priority", 0))}


class DisaggHandle:
    """Client-side pairing of the two role pools: `stream(prompt, ...)`
    routes the prompt to the prefill deployment (retrying `call` — a
    prefill replica killed mid-handoff fails over through the standard
    replica-death retry), then resumes the descriptor on the decode
    deployment as a token stream with the disagg failover policy."""

    def __init__(self, prefill_handle, decode_handle):
        self.prefill = prefill_handle
        self.decode = decode_handle

    def options(self, *, priority: int | None = None) -> "DisaggHandle":
        return DisaggHandle(
            self.prefill.options(priority=priority)
            if priority is not None else self.prefill,
            self.decode.options(priority=priority)
            if priority is not None else self.decode)

    def stream(self, prompt, max_new_tokens: int = 8,
               temperature: float = 0.0, *, timeout: float = 120.0,
               deadline_s: float | None = None, **kw):
        desc = self.prefill.call(
            list(prompt), max_new_tokens=max_new_tokens,
            temperature=temperature, timeout=timeout,
            deadline_s=deadline_s, **kw)
        return self.decode.stream(
            desc, timeout=timeout, deadline_s=deadline_s,
            failover=disagg_resume)

    def generate(self, prompt, max_new_tokens: int = 8, **kw) -> list:
        return list(self.stream(prompt, max_new_tokens=max_new_tokens,
                                **kw))
