"""ServeController — the control plane.

Counterpart of the reference's `ServeController`
(`serve/controller.py:82`) with its `DeploymentStateManager`
(`_private/deployment_state.py:2127`): a detached named actor that
reconciles desired deployment specs into replica actors, runs health
checks, and autoscales on queue depth. Replica-set changes are versioned;
handles poll `get_replicas` with their last seen version (the pull
analogue of the reference's long-poll push, `_private/long_poll.py:187`).

Concurrency model: control RPCs (running on the actor's thread pool) only
record desired state under the lock; ALL replica actor creation/teardown
happens on the single reconcile thread, so replica sets cannot be
mutated concurrently and a mid-flight redeploy cannot leak actors.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

import ray_tpu
from ray_tpu import exceptions as _exc

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "SERVE_CONTROLLER"
from ray_tpu._private.constants import (
    SERVE_BREAKER_COOLDOWN_S,
    SERVE_BREAKER_PROBE_S,
    SERVE_BREAKER_THRESHOLD,
    SERVE_BREAKER_WINDOW_S,
    SERVE_DOWNSCALE_DELAY_S,
    SERVE_DRAIN_POLL_S,
    SERVE_DRAIN_TIMEOUT_S,
    SERVE_HEALTH_FAILURE_THRESHOLD,
    SERVE_HEALTH_STARTUP_GRACE_S,
    SERVE_RECONCILE_PERIOD_S as _RECONCILE_PERIOD_S,
    SERVE_STATS_TIMEOUT_S,
)


class _DeploymentState:
    def __init__(self, name: str, app_name: str, spec: dict):
        self.name = name
        self.app_name = app_name
        self.spec = spec
        self.replicas: list = []
        self.version = 0
        self.target_num = spec.get("num_replicas", 1)
        self.autoscaling = spec.get("autoscaling_config")
        self.status = "UPDATING"
        self.message = ""
        # set by deploy_application on redeploy; consumed by reconcile
        self.pending_spec: dict | None = None
        # autoscaling smoothing (reference: autoscaling_policy.py
        # downscale_delay_s): scale down only after sustained low demand.
        self._downscale_candidate_since: float | None = None
        # autoscaler observability: the demand the last reconcile tick
        # computed (None = never scraped) and the error that aborted the
        # last scrape (None = the scrape worked) — surfaced in status()
        # so "never scaled up" is diagnosable from the outside.
        self.last_demand: float | None = None
        self.peak_demand: float = 0.0
        self.last_autoscale_error: str | None = None
        self.autoscale_ticks: int = 0
        # live latency view for SLO-aware admission (http_proxy): the
        # worst replica's TTFT/TPOT p99 from the last stats scrape,
        # None until engine-backed replicas report them.
        self.slo_snapshot: dict | None = None
        # circuit breaker over replica deaths: closed (normal restarts)
        # -> open (quarantine: deaths stop triggering restarts) ->
        # half_open (one probe replica) -> closed on probe survival.
        self.breaker = "closed"
        self.breaker_opened_at = 0.0
        self.death_times: collections.deque = collections.deque(maxlen=64)
        self.probe_id = None
        self.probe_since = 0.0


class ServeController:
    def __init__(self):
        self._deployments: dict = {}      # (app, name) -> _DeploymentState
        self._graveyard: list = []        # replica lists awaiting drain
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        # health plane knobs — instance state (seeded from constants) so
        # configure_fault_tolerance can tune a live controller
        self.health_failure_threshold = SERVE_HEALTH_FAILURE_THRESHOLD
        self.health_startup_grace_s = SERVE_HEALTH_STARTUP_GRACE_S
        self.breaker_threshold = SERVE_BREAKER_THRESHOLD
        self.breaker_window_s = SERVE_BREAKER_WINDOW_S
        self.breaker_cooldown_s = SERVE_BREAKER_COOLDOWN_S
        self.breaker_probe_s = SERVE_BREAKER_PROBE_S
        # per-replica health records (reconcile thread is sole writer)
        self._strikes: dict = {}          # actor_id -> consecutive fails
        self._born: dict = {}             # actor_id -> creation ts
        self._healthy: set = set()        # actor_ids that ever passed
        # fault-tolerance counters (stats() -> Prometheus bridge)
        self._breaker_trips = 0
        self._replicas_restarted = 0
        self._health_check_failures = 0
        from ray_tpu.util import telemetry as _telemetry
        self._telemetry_name = _telemetry.register_stats_source(
            _telemetry.next_name("serve_controller#"), self,
            kind="serve_controller")
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._thread.start()

    # -- control RPCs (record desired state only) -------------------------

    def deploy_application(self, app_name: str, deployments: list) -> bool:
        with self._lock:
            new_names = {d["name"] for d in deployments}
            for key in [k for k in self._deployments
                        if k[0] == app_name and k[1] not in new_names]:
                st = self._deployments.pop(key)
                self._graveyard.append(st.replicas)
                st.replicas = []
            for spec in deployments:
                key = (app_name, spec["name"])
                cur = self._deployments.get(key)
                if cur is None:
                    self._deployments[key] = _DeploymentState(
                        spec["name"], app_name, spec)
                else:
                    cur.pending_spec = spec
                    cur.status = "UPDATING"
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            for key in [k for k in self._deployments if k[0] == app_name]:
                st = self._deployments.pop(key)
                self._graveyard.append(st.replicas)
                st.replicas = []
        return True

    def get_replicas(self, deployment_name: str, app_name: str,
                     known_version: int):
        with self._lock:
            st = self._deployments.get((app_name, deployment_name))
            if st is None:
                return (0, [])
            if st.version == known_version:
                return None
            return (st.version, list(st.replicas))

    def get_routes(self) -> dict:
        """route_prefix -> (deployment, app) for every routed deployment."""
        with self._lock:
            out = {}
            for (app, name), st in self._deployments.items():
                prefix = st.spec.get("route_prefix")
                if prefix:
                    out[prefix] = (name, app)
            return out

    def status(self) -> dict:
        with self._lock:
            return {
                f"{app}:{name}": {
                    "status": st.status,
                    "message": st.message,
                    "replicas": len(st.replicas),
                    "target_replicas": st.target_num,
                    "breaker": st.breaker,
                    "last_demand": st.last_demand,
                    "peak_demand": st.peak_demand,
                    "autoscale_ticks": st.autoscale_ticks,
                    "last_autoscale_error": st.last_autoscale_error,
                }
                for (app, name), st in self._deployments.items()
            }

    @staticmethod
    def _update_slo_snapshot(st: _DeploymentState,
                             replica_stats: list) -> None:
        """Fold one stats scrape into the deployment's live latency view
        (the proxy's SLO-admission input). Worst replica wins — an SLO
        the slowest replica can't meet isn't met, since the router may
        pick any of them."""
        ttft = [s["ttft_ms_p99"] for s in replica_stats
                if isinstance(s.get("ttft_ms_p99"), (int, float))]
        tpot = [s["p99_token_latency_ms"] for s in replica_stats
                if isinstance(s.get("p99_token_latency_ms"),
                              (int, float))]
        if not ttft and not tpot:
            return
        st.slo_snapshot = {
            "ttft_ms_p99": max(ttft) if ttft else 0.0,
            "tpot_ms_p99": max(tpot) if tpot else 0.0,
            "queue_depth": sum(s.get("queue_depth", 0)
                               for s in replica_stats),
            "replicas": len(replica_stats),
        }

    def get_slo_snapshot(self) -> dict:
        """`"app:deployment" -> {ttft_ms_p99, tpot_ms_p99, queue_depth,
        replicas}` for every deployment whose replicas report latency
        histograms (engine-backed ones do). The HTTP proxy caches this
        briefly and admits/sheds per-request SLO targets against it."""
        with self._lock:
            return {f"{app}:{name}": dict(st.slo_snapshot)
                    for (app, name), st in self._deployments.items()
                    if st.slo_snapshot is not None}

    def stats(self) -> dict:
        """Serve-plane fault-tolerance counters, published to /metrics
        through the stats->Prometheus bridge as ``serve_controller_*``
        series (see util/telemetry.py).

        - ``breaker_trips``: circuit-breaker open transitions across all
          deployments (closed->open and half_open->open both count).
        - ``replicas_restarted``: crashed/struck-out replicas replaced
          by reconcile (quarantined deaths are NOT restarted, so they
          don't count).
        - ``health_check_failures``: individual failed health pings,
          including transient strikes that did not kill the replica.
        - ``quarantined``: deployments whose breaker is currently open.
        - ``deployments``: deployments under management.
        """
        with self._lock:
            return {
                "breaker_trips": self._breaker_trips,
                "replicas_restarted": self._replicas_restarted,
                "health_check_failures": self._health_check_failures,
                "quarantined": sum(
                    1 for st in self._deployments.values()
                    if st.breaker == "open"),
                "deployments": len(self._deployments),
            }

    def configure_fault_tolerance(self, **knobs) -> dict:
        """Tune the live health plane (tests shrink windows; the
        RAY_TPU_SERVE_* env constants are read at import time, so a
        per-test override needs this RPC). Accepts any of:
        health_failure_threshold, health_startup_grace_s,
        breaker_threshold, breaker_window_s, breaker_cooldown_s,
        breaker_probe_s. Returns the effective settings."""
        allowed = ("health_failure_threshold", "health_startup_grace_s",
                   "breaker_threshold", "breaker_window_s",
                   "breaker_cooldown_s", "breaker_probe_s")
        for k, v in knobs.items():
            if k not in allowed:
                raise ValueError(f"unknown fault-tolerance knob: {k!r}")
            setattr(self, k, type(getattr(self, k))(v))
        return {k: getattr(self, k) for k in allowed}

    def inject_faults(self, plan) -> bool:
        """Install a `util.faults.FaultPlan` in the CONTROLLER process
        (sites like ``controller.health_ping``); None clears it."""
        from ray_tpu.util import faults
        if plan is None:
            faults.clear()
        else:
            faults.install(plan)
        return True

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        # Snapshot-and-clear under the lock, kill outside it:
        # _kill_replicas blocks up to the prepare_shutdown timeout per
        # batch, and status()/route_table() RPCs must not stall behind
        # the teardown (graftlint R004 pins this).
        doomed: list[list] = []
        with self._lock:
            for st in self._deployments.values():
                doomed.append(st.replicas)
                st.replicas = []
            self._deployments.clear()
            doomed.extend(self._graveyard)
            self._graveyard.clear()
        for replicas in doomed:
            self._kill_replicas(replicas)
        return True

    def ping(self) -> bool:
        return True

    # -- reconciliation (sole mutator of replica sets) --------------------

    def _kill_replicas(self, replicas: list) -> None:
        # Best-effort graceful teardown, then kill (reference: replicas
        # get a graceful_shutdown call before force-kill,
        # deployment_state.py).
        pending = []
        for r in replicas:
            try:
                pending.append(r.prepare_shutdown.remote())
            except _exc.RayTpuError:
                pass
        if pending:
            try:
                ray_tpu.wait(pending, num_returns=len(pending), timeout=5)
            except _exc.RayTpuError:
                pass
        for r in replicas:
            try:
                ray_tpu.kill(r)
            except _exc.RayTpuError:
                pass

    def _drain_replicas(self, replicas: list) -> None:
        """Block until every victim reports zero in-flight requests AND
        zero live response streams (or the drain deadline passes). Only
        called after the shrunk replica set was published, so no new
        work can arrive at a victim while it drains."""
        deadline = time.time() + SERVE_DRAIN_TIMEOUT_S
        remaining = list(replicas)
        while remaining and time.time() < deadline:
            busy = []
            for r in remaining:
                try:
                    s = ray_tpu.get(r.stats.remote(),
                                    timeout=SERVE_STATS_TIMEOUT_S)
                    if s.get("inflight", 0) > 0 or \
                            s.get("streams", 0) > 0:
                        busy.append(r)
                except _exc.RayTpuError:
                    pass   # dead/unreachable — nothing left to drain
            remaining = busy
            if remaining:
                time.sleep(SERVE_DRAIN_POLL_S)
        if remaining:
            logger.warning("%d replica(s) still busy at drain deadline",
                           len(remaining))

    def _make_replica(self, st: _DeploymentState):
        from ray_tpu.serve.replica import Replica
        opts = dict(st.spec.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0.1)
        opts["max_concurrency"] = st.spec.get("max_concurrent_queries", 8)
        actor_cls = ray_tpu.remote(**opts)(Replica)
        r = actor_cls.remote({
            "callable": st.spec["callable"],
            "init_args": st.spec.get("init_args", ()),
            "init_kwargs": st.spec.get("init_kwargs", {}),
            "deployment_name": st.name,
            "max_concurrent_queries":
                st.spec.get("max_concurrent_queries", 8),
            "default_priority": st.spec.get("default_priority", 0),
        })
        self._born[r._actor_id] = time.time()
        return r

    def _health_check(self, replicas: list) -> tuple[list, list]:
        """Parallel, strike-based health checks.

        Returns ``(alive, deaths)``. A replica only moves to ``deaths``
        when its death is authoritative (the actor table says so:
        ActorDiedError / WorkerCrashedError) or it has failed
        ``health_failure_threshold`` CONSECUTIVE pings — one transient
        blip (GC pause, long engine tick) no longer kills a warm
        replica. Replicas that never passed a ping get a startup grace
        window (``health_startup_grace_s``) during which soft failures
        don't strike; real crashes still count immediately.
        """
        from ray_tpu.util import faults
        now = time.time()
        round_down = False
        try:
            # fault site: the CONTROLLER's probe fan-out fails this round
            # (e.g. a partitioned control plane) — every replica looks
            # unreachable at once; strikes must absorb it.
            faults.check("controller.health_ping")
        except faults.FaultInjected:
            round_down = True
        futs, dead, soft = {}, [], []
        if not round_down:
            for r in replicas:
                try:
                    futs[r.check_health.remote()] = r
                except _exc.RayTpuError:
                    dead.append(r)     # can't even submit: authoritative
        alive = []
        if futs:
            ready, not_ready = ray_tpu.wait(
                list(futs), num_returns=len(futs), timeout=10)
            for fut in ready:
                r = futs[fut]
                try:
                    ray_tpu.get(fut)
                    aid = r._actor_id
                    self._strikes.pop(aid, None)
                    self._healthy.add(aid)
                    alive.append(r)
                except (_exc.ActorDiedError, _exc.WorkerCrashedError):
                    dead.append(r)     # actor table: authoritative
                except _exc.RayTpuError:
                    soft.append(r)     # user check_health raised: strike
            for fut in not_ready:
                soft.append(futs[fut])  # ping timed out: strike
        else:
            soft.extend(replicas)
        for r in soft:
            aid = r._actor_id
            self._health_check_failures += 1
            if aid not in self._healthy and \
                    now - self._born.get(aid, now) < \
                    self.health_startup_grace_s:
                alive.append(r)        # still starting up: probation
                continue
            strikes = self._strikes.get(aid, 0) + 1
            self._strikes[aid] = strikes
            if strikes >= self.health_failure_threshold:
                logger.warning(
                    "replica %s failed %d consecutive health checks",
                    aid, strikes)
                dead.append(r)
            else:
                logger.warning(
                    "replica %s failed health check (strike %d/%d)",
                    aid, strikes, self.health_failure_threshold)
                alive.append(r)
        for r in dead:
            aid = r._actor_id
            self._strikes.pop(aid, None)
            self._born.pop(aid, None)
            self._healthy.discard(aid)
        return alive, dead

    def _trip_breaker(self, st: _DeploymentState, now: float) -> None:
        with self._lock:
            st.breaker = "open"
            st.breaker_opened_at = now
            st.probe_id = None
            st.probe_since = 0.0
            self._breaker_trips += 1
            st.message = (f"circuit breaker open: {len(st.death_times)} "
                          f"replica deaths within {self.breaker_window_s}s")
        logger.warning("deployment %s:%s quarantined (%s)",
                       st.app_name, st.name, st.message)

    def _update_breaker(self, st: _DeploymentState, deaths: list,
                        now: float) -> None:
        """Advance the per-deployment circuit breaker.

        closed: deaths within ``breaker_window_s`` accumulate; at
        ``breaker_threshold`` the breaker opens (replacements stop — a
        crash-looping deployment must not burn the cluster respawning).
        open: after ``breaker_cooldown_s`` move to half_open.
        half_open: reconcile creates exactly ONE probe replica; if it
        stays healthy for ``breaker_probe_s`` the breaker closes and the
        death history clears, if it dies the breaker re-opens.
        """
        probe_died = st.probe_id is not None and any(
            r._actor_id == st.probe_id for r in deaths)
        if st.breaker == "closed":
            recent = [t for t in st.death_times
                      if now - t <= self.breaker_window_s]
            if len(recent) >= self.breaker_threshold:
                self._trip_breaker(st, now)
        elif st.breaker == "open":
            if now - st.breaker_opened_at >= self.breaker_cooldown_s:
                with self._lock:
                    st.breaker = "half_open"
                    st.probe_id = None
                    st.probe_since = 0.0
        elif st.breaker == "half_open":
            if probe_died:
                self._trip_breaker(st, now)
            elif (st.probe_id is not None and st.probe_since
                  and st.probe_id in self._healthy
                  and now - st.probe_since >= self.breaker_probe_s):
                with self._lock:
                    st.breaker = "closed"
                    st.death_times.clear()
                    st.probe_id = None
                    st.probe_since = 0.0
                    st.message = ""
                logger.info("deployment %s:%s breaker closed after "
                            "healthy probe", st.app_name, st.name)

    def _reconcile_one(self, st: _DeploymentState) -> None:
        # adopt a pending redeploy: retire every old replica
        pending = None
        with self._lock:
            if st.pending_spec is not None:
                pending = st.pending_spec
                st.pending_spec = None
        if pending is not None:
            old = st.replicas
            st.spec = pending
            st.target_num = pending.get("num_replicas", 1)
            st.autoscaling = pending.get("autoscaling_config")
            self._kill_replicas(old)
            with self._lock:
                st.replicas = []
                st.version += 1

        alive, deaths = self._health_check(st.replicas)
        changed = len(alive) != len(st.replicas)
        now = time.time()
        if deaths:
            st.death_times.extend(now for _ in deaths)
            # struck-out replicas may still be live processes wedged in a
            # bad state — reap them so they can't linger half-attached
            # (authoritative-dead ones make this a fast no-op)
            self._kill_replicas(deaths)
        self._update_breaker(st, deaths, now)

        replica_stats = None
        if alive:
            # Scrape every deployment, not just autoscaled ones: the
            # stats feed BOTH the autoscaler's demand signal and the
            # SLO-admission latency snapshot the proxy routes against.
            try:
                replica_stats = ray_tpu.get(
                    [r.stats.remote() for r in alive],
                    timeout=SERVE_STATS_TIMEOUT_S)
                self._update_slo_snapshot(st, replica_stats)
            except _exc.RayTpuError as e:
                if st.autoscaling:
                    st.last_autoscale_error = f"{type(e).__name__}: {e}"
        if st.autoscaling and replica_stats:
            # Demand signal is role-aware (disaggregated serving):
            #   "queue_depth" (default) = requests being served +
            #     requests queued behind them — the prefill pool's
            #     signal (queue pressure scales up BEFORE latency
            #     collapses, not after);
            #   "streams" = live response streams + queue — the decode
            #     pool's signal (a decode replica's load is its resident
            #     token streams, which stay open long after the
            #     admitting request returned).
            if st.autoscaling.get("demand_signal") == "streams":
                demand = sum(s.get("streams", 0)
                             + s.get("queue_depth", 0)
                             for s in replica_stats)
            else:
                demand = sum(s["inflight"] + s.get("queue_depth", 0)
                             for s in replica_stats)
            st.last_demand = demand
            st.peak_demand = max(st.peak_demand, demand)
            st.autoscale_ticks += 1
            st.last_autoscale_error = None
            target_per = st.autoscaling.get(
                "target_num_ongoing_requests_per_replica", 1.0)
            desired = int(max(
                st.autoscaling.get("min_replicas", 1),
                min(st.autoscaling.get("max_replicas", 8),
                    -(-demand // max(target_per, 1e-6))
                    or st.autoscaling.get("min_replicas", 1))))
            if desired >= len(alive):
                st.target_num = desired
                st._downscale_candidate_since = None
            else:
                delay = st.autoscaling.get("downscale_delay_s",
                                           SERVE_DOWNSCALE_DELAY_S)
                now = time.time()
                if st._downscale_candidate_since is None:
                    st._downscale_candidate_since = now
                elif now - st._downscale_candidate_since >= delay:
                    st.target_num = desired
                    st._downscale_candidate_since = None

        # breaker gates replacement: open = no new replicas at all
        # (quarantine), half_open = at most one probe beyond survivors
        allow = st.target_num
        if st.breaker == "open":
            allow = len(alive)
        elif st.breaker == "half_open":
            allow = min(st.target_num,
                        len(alive) + (0 if st.probe_id is not None else 1))
        n_created = 0
        while len(alive) < allow:
            r = self._make_replica(st)
            alive.append(r)
            changed = True
            n_created += 1
            if st.breaker == "half_open" and st.probe_id is None:
                with self._lock:
                    st.probe_id = r._actor_id
                    st.probe_since = time.time()
        if deaths and n_created:
            with self._lock:
                self._replicas_restarted += min(len(deaths), n_created)
        if len(alive) > st.target_num:
            if replica_stats and len(replica_stats) == len(alive):
                order = sorted(range(len(alive)),
                               key=lambda i: replica_stats[i]["inflight"])
                alive = [alive[i] for i in order]
            victims = alive[st.target_num:] if replica_stats is None \
                else alive[:len(alive) - st.target_num]
            alive = [r for r in alive if r not in victims]
            changed = True
            # Publish the shrunk replica set BEFORE touching the
            # victims: handles refresh off the bumped version and stop
            # routing to them, then the drain loop waits for their
            # in-flight requests and response streams to finish —
            # scale-down never truncates a token stream.
            with self._lock:
                if self._deployments.get((st.app_name, st.name)) is st:
                    st.replicas = list(alive)
                    st.version += 1
            self._drain_replicas(victims)
            self._kill_replicas(victims)

        with self._lock:
            # a concurrent delete/redeploy moved this state aside: retire
            # whatever we just created instead of leaking it
            if self._deployments.get((st.app_name, st.name)) is not st:
                self._graveyard.append(alive)
                return
            st.replicas = alive
            if changed:
                st.version += 1
            st.status = ("QUARANTINED" if st.breaker == "open"
                         else "RUNNING" if len(alive) == st.target_num
                         else "UPDATING")

    def _reconcile_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
            graveyard, self._graveyard = self._graveyard, []
        for replicas in graveyard:
            self._kill_replicas(replicas)
        for st in states:
            try:
                self._reconcile_one(st)
            except Exception:
                logger.exception("reconcile of %s failed", st.name)
        # drop health records for replicas retired by scale-down/redeploy
        # (death-path records are cleaned inline by _health_check)
        with self._lock:
            live = {r._actor_id for s in self._deployments.values()
                    for r in s.replicas}
        for rec in (self._strikes, self._born):
            for aid in [a for a in rec if a not in live]:
                rec.pop(aid, None)
        self._healthy &= live

    def _reconcile_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("reconcile step failed")
            self._shutdown.wait(_RECONCILE_PERIOD_S)


def get_controller():
    """Look up (or lazily create) the controller actor."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except (KeyError, ValueError, _exc.RayTpuError):
        return start_controller()


def start_controller():
    actor_cls = ray_tpu.remote(
        num_cpus=0.1, name=CONTROLLER_NAME, max_concurrency=16,
        lifetime="detached")(ServeController)
    controller = actor_cls.remote()
    ray_tpu.get(controller.ping.remote(), timeout=60)
    return controller
