"""Public Serve API.

Counterpart of the reference's `serve/api.py` (`@serve.deployment` :242,
`serve.run` :414, `serve.start` :62) and the `.bind()` application graph
(`serve/deployment.py`, `_private/deployment_graph_build.py`): bound
deployments referenced in another deployment's init args are delivered
as DeploymentHandles at replica construction (model composition).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


@dataclass
class Application:
    """A bound deployment graph rooted at an ingress deployment."""
    root: "BoundDeployment"

    def _collect(self) -> list:
        seen: dict = {}

        def walk(node: "BoundDeployment"):
            if id(node) in seen:
                return
            for a in list(node.init_args) + list(
                    node.init_kwargs.values()):
                if isinstance(a, BoundDeployment):
                    walk(a)
            seen[id(node)] = node

        walk(self.root)
        return list(seen.values())


class BoundDeployment:
    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    @property
    def name(self) -> str:
        return self.deployment.name


class Deployment:
    """The declarative unit (reference: serve/deployment.py Deployment)."""

    def __init__(self, target: Callable, name: str,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[dict] = None,
                 max_concurrent_queries: int = 8,
                 autoscaling_config: Optional[dict] = None,
                 route_prefix: Optional[str] = None,
                 default_priority: int = 0):
        self._target = target
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = dict(ray_actor_options or {})
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.route_prefix = route_prefix
        self.default_priority = default_priority

    def options(self, **opts) -> "Deployment":
        merged = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "ray_actor_options": self.ray_actor_options,
            "max_concurrent_queries": self.max_concurrent_queries,
            "autoscaling_config": self.autoscaling_config,
            "route_prefix": self.route_prefix,
            "default_priority": self.default_priority,
        }
        merged.update(opts)
        return Deployment(self._target, **merged)

    def bind(self, *args, **kwargs) -> "BoundDeployment":
        """Returns a graph node: pass it to serve.run as the app root, or
        as an init arg of another bind (it arrives as a handle)."""
        return BoundDeployment(self, args, kwargs)

    def to_spec(self, init_args: tuple, init_kwargs: dict,
                route_prefix: Optional[str]) -> dict:
        return {
            "name": self.name,
            "callable": self._target,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "num_replicas": self.num_replicas,
            "ray_actor_options": self.ray_actor_options,
            "max_concurrent_queries": self.max_concurrent_queries,
            "autoscaling_config": self.autoscaling_config,
            "route_prefix": route_prefix,
            "default_priority": self.default_priority,
        }

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "deployments are not called directly; use .bind() + serve.run, "
            "then handle.remote()")


def deployment(target=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               max_concurrent_queries: int = 8,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = None,
               default_priority: int = 0):
    """`@serve.deployment` decorator (bare or with options).

    `default_priority` is the priority class stamped on requests that
    don't carry one of their own (serve/priority.py)."""

    def wrap(t):
        return Deployment(t, name or t.__name__,
                          num_replicas=num_replicas,
                          ray_actor_options=ray_actor_options,
                          max_concurrent_queries=max_concurrent_queries,
                          autoscaling_config=autoscaling_config,
                          route_prefix=route_prefix,
                          default_priority=default_priority)

    if target is not None:
        return wrap(target)
    return wrap


# ---------------------------------------------------------------------------
# run / start / shutdown
# ---------------------------------------------------------------------------

_http_proxy = None


def run(app: Application, *, name: str = "default",
        route_prefix: str = "/", _blocking: bool = False
        ) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment
    (reference: serve.run, api.py:414)."""
    from ray_tpu.serve.controller import get_controller
    if isinstance(app, BoundDeployment):
        app = Application(app)
    controller = get_controller()

    nodes = app._collect()
    specs = []
    for node in nodes:
        # bound-deployment init args become handles (composition)
        init_args = tuple(
            DeploymentHandle(a.name, name) if isinstance(a, BoundDeployment)
            else a for a in node.init_args)
        init_kwargs = {
            k: (DeploymentHandle(v.name, name)
                if isinstance(v, BoundDeployment) else v)
            for k, v in node.init_kwargs.items()}
        prefix = route_prefix if node is app.root else \
            node.deployment.route_prefix
        specs.append(node.deployment.to_spec(init_args, init_kwargs, prefix))

    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    handle = DeploymentHandle(app.root.name, name)
    # wait for the ingress to be live
    handle._pick_replica()
    return handle


def run_disagg(cfg_kwargs: Optional[dict] = None, *,
               name: str = "default",
               prefill_replicas: int = 1, decode_replicas: int = 1,
               slots: int = 4, max_len: int = 64, seed: int = 0,
               engine_kwargs: Optional[dict] = None,
               prefill_autoscaling: Optional[dict] = None,
               decode_autoscaling: Optional[dict] = None,
               max_concurrent_queries: int = 8):
    """Deploy a disaggregated prefill/decode inference fleet: one
    `PrefillReplica` pool (chunked prefill + KV-block export) and one
    `DecodeReplica` pool (KV import + token streaming), paired behind a
    `DisaggHandle` — `handle.stream(prompt, n)` is greedy token-identical
    to a colocated `InferenceReplica` deployment of the same seed.

    The two pools autoscale independently on their own demand signals
    (see `ServeController`): prefill on queue depth (prompts waiting to
    be absorbed), decode on stream occupancy (live token streams) — pass
    `prefill_autoscaling` / `decode_autoscaling` dicts to enable; each
    gets the role's natural `demand_signal` unless overridden."""
    from ray_tpu.serve.controller import get_controller
    from ray_tpu.serve.disagg import (
        DecodeReplica,
        DisaggHandle,
        PrefillReplica,
    )
    if prefill_autoscaling is not None:
        prefill_autoscaling = {"demand_signal": "queue_depth",
                               **prefill_autoscaling}
    if decode_autoscaling is not None:
        decode_autoscaling = {"demand_signal": "streams",
                              **decode_autoscaling}
    init_kwargs = {"slots": slots, "max_len": max_len, "seed": seed,
                   "engine_kwargs": engine_kwargs}
    specs = [
        Deployment(PrefillReplica, "prefill",
                   num_replicas=prefill_replicas,
                   max_concurrent_queries=max_concurrent_queries,
                   autoscaling_config=prefill_autoscaling).to_spec(
            (cfg_kwargs,), init_kwargs, None),
        Deployment(DecodeReplica, "decode",
                   num_replicas=decode_replicas,
                   max_concurrent_queries=max_concurrent_queries,
                   autoscaling_config=decode_autoscaling).to_spec(
            (cfg_kwargs,), init_kwargs, None),
    ]
    controller = get_controller()
    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    prefill = DeploymentHandle("prefill", name)
    decode = DeploymentHandle("decode", name)
    prefill._pick_replica()      # block until both pools are live
    decode._pick_replica()
    return DisaggHandle(prefill, decode)


_node_proxies: dict = {}


def start(*, http_options: Optional[dict] = None):
    """Start HTTP ingress (reference: serve.start, api.py:62). With
    ``http_options={"location": "EveryNode"}`` one proxy actor runs on
    EVERY alive node, pinned by node affinity — the reference's
    per-node HTTPProxyActor layout (`_private/http_proxy.py:858`) for
    multi-host clusters where a load balancer fronts all hosts. The
    default ("HeadOnly") keeps one proxy."""
    global _http_proxy
    from ray_tpu.serve.controller import get_controller
    from ray_tpu.serve.http_proxy import HTTPProxy
    get_controller()
    opts = dict(http_options or {})
    from ray_tpu._private.constants import SERVE_HTTP_HOST, SERVE_HTTP_PORT
    host = opts.get("host", SERVE_HTTP_HOST)
    port = opts.get("port", SERVE_HTTP_PORT)
    if _http_proxy is None:
        actor_cls = ray_tpu.remote(
            num_cpus=0.1, max_concurrency=32,
            name="SERVE_HTTP_PROXY")(HTTPProxy)
        _http_proxy = actor_cls.remote(host, port)
        ray_tpu.get(_http_proxy.ready.remote(), timeout=60)
    if opts.get("location") == "EveryNode":
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )
        client = ray_tpu._worker.get_client()
        for n in client.control("list_nodes"):
            nid = n["node_id"]
            if not n.get("alive") or n.get("head") or nid in _node_proxies:
                continue
            cls = ray_tpu.remote(
                num_cpus=0.1, max_concurrency=32,
                name=f"SERVE_HTTP_PROXY_{nid}",
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=nid, soft=False))(HTTPProxy)
            # port=0 on worker nodes in single-machine tests; real pods
            # pass the same fixed port per host
            proxy = cls.remote(host, opts.get("worker_port", port))
            ray_tpu.get(proxy.ready.remote(), timeout=60)
            _node_proxies[nid] = proxy
    return _http_proxy


def proxy_endpoints() -> dict:
    """{node_id: {"host", "port"}} for every running proxy (the list a
    load balancer would front)."""
    out = {}
    if _http_proxy is not None:
        out["head"] = ray_tpu.get(_http_proxy.ready.remote(), timeout=30)
    for nid, proxy in _node_proxies.items():
        try:
            out[nid] = ray_tpu.get(proxy.ready.remote(), timeout=30)
        except Exception:
            pass
    return out


def set_route(route_prefix: str, deployment_name: str,
              app_name: str = "default"):
    """Register an HTTP route on every running proxy."""
    proxy = start()
    ray_tpu.get(proxy.set_route.remote(route_prefix, deployment_name,
                                       app_name), timeout=30)
    for p in _node_proxies.values():
        ray_tpu.get(p.set_route.remote(route_prefix, deployment_name,
                                       app_name), timeout=30)


def status() -> dict:
    from ray_tpu.serve.controller import get_controller
    return ray_tpu.get(get_controller().status.remote(), timeout=30)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def delete(name: str = "default"):
    from ray_tpu.serve.controller import get_controller
    ray_tpu.get(get_controller().delete_application.remote(name),
                timeout=60)


def shutdown():
    global _http_proxy
    from ray_tpu import exceptions as _exc
    from ray_tpu.serve.controller import CONTROLLER_NAME
    for proxy in list(_node_proxies.values()):
        try:
            ray_tpu.get(proxy.stop.remote(), timeout=10)
            ray_tpu.kill(proxy)
        except _exc.RayTpuError:
            pass
    _node_proxies.clear()
    if _http_proxy is not None:
        try:
            ray_tpu.get(_http_proxy.stop.remote(), timeout=10)
            ray_tpu.kill(_http_proxy)
        except _exc.RayTpuError:
            pass
        _http_proxy = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=60)
        ray_tpu.kill(controller)
    except (KeyError, ValueError, _exc.RayTpuError):
        pass
