"""Shared off-policy plumbing for SAC / DDPG / TD3 (and future family
members): the critic network, tanh-action scaling, replay-batch stacking
for fused K-update scans, and episode-return bookkeeping. One
implementation — these were identical in each algorithm and drift in one
copy would silently skew the others."""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class QNet(nn.Module):
    """Q(s, a) critic MLP (reference: ddpg/sac torch models)."""
    hiddens: Tuple[int, ...] = (256, 256)

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(1)(x)[..., 0]


def scale_action(low, high, act_tanh):
    """[-1, 1] policy output -> env action bounds."""
    return low + (act_tanh + 1.0) * 0.5 * (high - low)


def stack_replay_batches(buffer, k: int, batch_size: int) -> dict:
    """Sample k*batch_size transitions and reshape to [k, B, ...] so the
    learner scans K fused updates in one dispatch."""
    flat = buffer.sample(k * batch_size)
    return {
        name: jnp.asarray(v).reshape((k, batch_size) + v.shape[1:])
        for name, v in flat.items() if name != "batch_indexes"}


def drain_episode_returns(traj_host: dict, ep_returns: list,
                          cap: int = 100) -> dict:
    """Pop per-step `episode_return` (NaN = unfinished) from a host-side
    trajectory, fold finished returns into the rolling window, and return
    the remaining fields flattened to [T*B, ...]."""
    rets = traj_host.pop("episode_return").ravel()
    fin = ~np.isnan(rets)
    ep_returns.extend(rets[fin].tolist())
    del ep_returns[:-cap]
    return {k: v.reshape((-1,) + v.shape[2:])
            for k, v in traj_host.items()}
