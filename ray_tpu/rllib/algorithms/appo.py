"""APPO — asynchronous PPO (IMPALA architecture + clipped surrogate).

Counterpart of the reference's `rllib/algorithms/appo/` (appo.py: IMPALA
subclass; loss `appo_torch_policy.py`: PPO's clipped surrogate computed on
V-trace advantages with the behaviour policy as the old policy). Inherits
the async rollout pipeline from our IMPALA (one in-flight sample per
worker, learner consumes as batches land) and replaces the plain
policy-gradient term with the clipped surrogate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig, vtrace


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param = 0.4            # reference appo.py default
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.lr = 1e-3
        # The clipped surrogate exists to make batch reuse safe (that is
        # APPO's delta over IMPALA), so default to two SGD passes per
        # learner batch (reference: appo.py replays via
        # minibatch_buffer_size/num_sgd_iter on the learner thread).
        self.num_sgd_iter = 2


class APPO(IMPALA):
    _config_class = APPOConfig

    def _vtrace_loss(self, params, batch, last_value):
        """PPO's clipped surrogate on V-trace advantages; the behaviour
        policy's logp is the "old" policy (appo_torch_policy.py). Plugs
        into IMPALA's shared whole-batch/minibatched update loop."""
        cfg = self.algo_config
        dist, values = self.module.forward(params, batch[sb.OBS])
        target_logp = dist.logp(batch[sb.ACTIONS])
        vs, pg_adv = vtrace(
            batch[sb.ACTION_LOGP], target_logp, batch[sb.REWARDS],
            values, batch[sb.DONES], last_value, cfg.gamma,
            cfg.lambda_, cfg.vtrace_clip_rho_threshold,
            cfg.vtrace_clip_pg_rho_threshold)
        if cfg.standardize_advantages:
            pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
        ratio = jnp.exp(target_logp - batch[sb.ACTION_LOGP])
        surr = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1 - cfg.clip_param,
                     1 + cfg.clip_param) * pg_adv)
        pg_loss = -jnp.mean(surr)
        vf_loss = 0.5 * jnp.mean(jnp.square(vs - values))
        entropy = jnp.mean(dist.entropy())
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        if cfg.use_kl_loss:
            approx_kl = jnp.mean(batch[sb.ACTION_LOGP] - target_logp)
            total = total + cfg.kl_coeff * approx_kl
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


register_algorithm("APPO", APPO)
