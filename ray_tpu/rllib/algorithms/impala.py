"""IMPALA — importance-weighted actor-learner architecture.

Counterpart of the reference's `rllib/algorithms/impala/` (impala.py:
decoupled rollout actors feeding an async learner via
`execution/learner_thread.py` / `multi_gpu_learner_thread.py`; V-trace
`rllib/algorithms/impala/vtrace_torch.py`, after Espeholt et al. 2018).

Shape here: rollout actors run continuously with whatever weights they
last received (off-policy by a few versions); the learner consumes
batches as they arrive and corrects the lag with V-trace. The V-trace
backward pass is a `lax.scan` inside the jitted update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu import exceptions as _exc
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.worker_set import WorkerSet, merge_episode_stats


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 6e-4
        self.gamma = 0.99
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_pg_rho_threshold = 1.0
        self.lambda_ = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_rollout_workers = 2
        self.rollout_fragment_length = 64
        self.batches_per_step = 4       # learner batches per train() call
        self.broadcast_interval = 1     # resubmit with fresh weights every
        self.grad_clip = 40.0
        # SGD passes over each learner batch (reference: impala.py
        # num_sgd_iter + minibatch_buffer_size — the learner thread
        # replays a batch several times; V-trace's rho/c clipping absorbs
        # the growing policy lag)
        self.num_sgd_iter = 1
        # shuffled minibatches per pass (None = whole batch; reference:
        # impala.py minibatch_size)
        self.sgd_minibatch_size = None
        # optimizer family (reference: impala.py opt_type "adam"/"rmsprop")
        self.opt_type = "rmsprop"
        # standardize V-trace pg advantages per batch before the policy
        # loss — an extension borrowed from PPO's postprocessing
        # (reference: ppo.py standardize_fields); OFF by default to match
        # reference IMPALA, but the make-or-break stabilizer for sparse-
        # reward pixel tasks at small batch sizes
        self.standardize_advantages = False


def vtrace(behaviour_logp, target_logp, rewards, values, dones,
           last_value, gamma, lambda_, clip_rho, clip_pg_rho):
    """V-trace targets over a [T] or [T, B] fragment batch (Espeholt et
    al. 2018, eqns 1-2). All inputs time-major; `last_value` matches the
    trailing batch shape. Returns (vs, pg_advantages)."""
    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = lambda_ * jnp.minimum(1.0, rhos)
    nonterm = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], last_value[None]])
    deltas = clipped_rhos * (rewards + gamma * nonterm * next_values
                             - values)

    def back(acc, xs):
        delta, c, nt = xs
        acc = delta + gamma * nt * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(back, jnp.zeros_like(last_value),
                                 (deltas, cs, nonterm), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_value[None]])
    pg_adv = jnp.minimum(clip_pg_rho, rhos) * (
        rewards + gamma * nonterm * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALA(Algorithm):
    _config_class = IMPALAConfig

    def build_learner(self) -> None:
        cfg = self.algo_config
        chain = []
        if cfg.grad_clip:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        if getattr(cfg, "opt_type", "rmsprop") == "adam":
            chain.append(optax.adam(cfg.lr))
        else:
            chain.append(optax.rmsprop(cfg.lr, decay=0.99, eps=0.1))
        self.optimizer = optax.chain(*chain)
        self.opt_state = self.optimizer.init(self.params)

        env_spec, env_cfg, model_cfg = (cfg.env, dict(cfg.env_config),
                                        dict(cfg.model))
        from ray_tpu.rllib.core.rl_module import RLModule
        from ray_tpu.rllib.env.jax_env import make_env

        def env_creator(worker_index, _s=env_spec, _c=env_cfg):
            return make_env(_s, _c)

        def module_creator(env, _mc=model_cfg):
            return RLModule(env.observation_space, env.action_space, _mc)

        self.workers = WorkerSet(
            max(1, cfg.num_rollout_workers), env_creator, module_creator,
            cfg.rollout_fragment_length, seed=cfg.seed,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            connectors=cfg.connector_dict(),
            num_envs_per_worker=cfg.num_envs_per_worker)
        self._update_fn = jax.jit(self._vtrace_update)
        # async pipeline: one in-flight sample per worker
        self._inflight: dict = {}
        self._steps_trained = 0

    def _vtrace_loss(self, params, batch, last_value):
        """(loss, stats) for one fragment (mini)batch. Shared by the
        whole-batch and minibatched passes; APPO overrides this with the
        clipped surrogate."""
        cfg = self.algo_config
        dist, values = self.module.forward(params, batch[sb.OBS])
        target_logp = dist.logp(batch[sb.ACTIONS])
        vs, pg_adv = vtrace(
            batch[sb.ACTION_LOGP], target_logp, batch[sb.REWARDS],
            values, batch[sb.DONES], last_value, cfg.gamma,
            cfg.lambda_, cfg.vtrace_clip_rho_threshold,
            cfg.vtrace_clip_pg_rho_threshold)
        if cfg.standardize_advantages:
            pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
        pg_loss = -jnp.mean(target_logp * pg_adv)
        vf_loss = 0.5 * jnp.mean(jnp.square(vs - values))
        entropy = jnp.mean(dist.entropy())
        total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    def _vtrace_update(self, params, opt_state, batch, last_value, key):
        """num_sgd_iter epochs over the batch; when sgd_minibatch_size is
        set and fragments are [T, B], each epoch is a shuffled scan over
        env-column minibatches (fragments stay whole so V-trace sees full
        sequences — reference: impala.py num_sgd_iter/minibatch_size)."""
        cfg = self.algo_config

        def sgd_step(state, mb):
            params, opt_state = state
            b, lv = mb
            (_, stats), grads = jax.value_and_grad(
                self._vtrace_loss, has_aux=True)(params, b, lv)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), stats

        t_b = batch[sb.REWARDS].ndim
        mb_size = cfg.sgd_minibatch_size
        if t_b == 2 and mb_size:
            T, B = batch[sb.REWARDS].shape
            cols = min(B, max(1, int(mb_size) // T))
            num_mb = max(1, B // cols)

            def one_epoch(state, ekey):
                perm = jax.random.permutation(ekey, B)[:num_mb * cols]

                def shuf(v):
                    v = v[:, perm]
                    v = v.reshape(v.shape[0], num_mb, cols, *v.shape[2:])
                    return jnp.moveaxis(v, 1, 0)   # [num_mb, T, cols, ..]

                mbs = jax.tree.map(shuf, dict(batch))
                lvs = last_value[perm].reshape(num_mb, cols)
                state, stats = jax.lax.scan(sgd_step, state, (mbs, lvs))
                return state, jax.tree.map(jnp.mean, stats)

            epoch_keys = jax.random.split(key, max(1, cfg.num_sgd_iter))
            (params, opt_state), stats = jax.lax.scan(
                one_epoch, (params, opt_state), epoch_keys)
        else:
            def one_pass(state, _):
                return sgd_step(state, (batch, last_value))

            (params, opt_state), stats = jax.lax.scan(
                one_pass, (params, opt_state), None,
                length=max(1, cfg.num_sgd_iter))
        return params, opt_state, jax.tree.map(jnp.mean, stats)

    def _submit(self, idx: int) -> None:
        from ray_tpu.rllib.worker_set import _to_host
        w = self.workers._workers[idx]
        params_ref = ray_tpu.put(_to_host(self.params))
        self._inflight[w.sample_with_weights.remote(params_ref)] = idx

    def training_step(self) -> dict:
        cfg = self.algo_config
        for i in range(len(self.workers._workers)):
            if i not in self._inflight.values():
                self._submit(i)

        stats_list, learn_stats = [], []
        consumed = 0
        while consumed < cfg.batches_per_step:
            ready, _ = ray_tpu.wait(list(self._inflight),
                                    num_returns=1, timeout=120)
            if not ready:
                break
            fut = ready[0]
            idx = self._inflight.pop(fut)
            try:
                batch, last_v, ep_stats = ray_tpu.get(fut)
            except _exc.RayTpuError:
                self.workers._restart(idx)
                self._submit(idx)
                continue
            self._submit(idx)       # keep the actor busy (async pipeline)
            device = {k: jnp.asarray(v) for k, v in batch.items()}
            self.params, self.opt_state, stats = self._update_fn(
                self.params, self.opt_state, device,
                jnp.asarray(last_v), self.next_key())
            learn_stats.append(stats)
            stats_list.append(ep_stats)
            consumed += 1
            # rewards count env steps for both [T] and [T, B] fragments
            self._steps_trained += int(np.asarray(batch[sb.REWARDS]).size)

        metrics = merge_episode_stats(stats_list) if stats_list else {
            "episode_reward_mean": float("nan"), "episodes_this_iter": 0}
        if learn_stats:
            mean = jax.tree.map(
                lambda *xs: float(np.mean([np.asarray(x) for x in xs])),
                *learn_stats)
            metrics.update(mean)
        metrics["num_env_steps_trained"] = self._steps_trained
        return metrics

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("IMPALA", IMPALA)
