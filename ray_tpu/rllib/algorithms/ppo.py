"""PPO — proximal policy optimization.

Counterpart of the reference's `rllib/algorithms/ppo/` (ppo.py:420
training_step; loss `ppo_torch_policy.py`: clipped surrogate + vf loss +
entropy; GAE `rllib/evaluation/postprocessing.py`). TPU-first shape:

- JaxEnv path: rollout (vmap+scan), GAE (reverse scan), and the full
  num_sgd_iter × minibatch SGD loop are ONE jitted function — the whole
  PPO iteration is a single XLA program; Python only reads metrics.
- Python-env path: WorkerSet actors sample; GAE on host; the same jitted
  update consumes the concatenated batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import is_jax_env
from ray_tpu.rllib.rollout import InGraphSampler, episode_stats
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae, concat_samples
from ray_tpu.rllib.worker_set import WorkerSet, merge_episode_stats


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 512
        self.rollout_fragment_length = 128
        self.num_envs_per_worker = 16
        self.grad_clip = 0.5
        # >1: shard the WHOLE fused iteration (rollout + GAE + SGD) over
        # a data-axis device mesh via shard_map — env batch split across
        # devices, gradients pmean'd over ICI. The TPU-native analogue of
        # the reference's multi-GPU learner stack
        # (rllib/execution/multi_gpu_learner_thread.py), except sampling
        # shards too, not just the SGD pass.
        self.num_learner_devices = 0


def _ppo_loss(module, params, batch, clip_param, vf_clip_param,
              vf_loss_coeff, entropy_coeff):
    dist, value = module.forward(params, batch[sb.OBS])
    logp = dist.logp(batch[sb.ACTIONS])
    ratio = jnp.exp(logp - batch[sb.ACTION_LOGP])
    adv = batch[sb.ADVANTAGES]
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
    policy_loss = -jnp.mean(surr)
    # Clipped value loss (reference: ppo_torch_policy.py vf_clip_param).
    vf_err = jnp.square(value - batch[sb.VALUE_TARGETS])
    vf_loss = jnp.mean(jnp.clip(vf_err, 0.0, vf_clip_param ** 2))
    entropy = jnp.mean(dist.entropy())
    total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
    stats = {"policy_loss": policy_loss, "vf_loss": vf_loss,
             "entropy": entropy,
             "approx_kl": jnp.mean(batch[sb.ACTION_LOGP] - logp)}
    return total, stats


def _gae_scan(rewards, values, dones, last_value, gamma, lam):
    """In-graph GAE: reverse lax.scan over time. rewards/values/dones are
    [T, B]; last_value [B]."""

    def back(carry, xs):
        r, v, d, next_v = xs
        nonterm = 1.0 - d.astype(jnp.float32)
        delta = r + gamma * next_v * nonterm - v
        adv = delta + gamma * lam * nonterm * carry
        return adv, adv

    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    _, advs = jax.lax.scan(back, jnp.zeros_like(last_value),
                           (rewards, values, dones, next_values),
                           reverse=True)
    return advs


class PPO(Algorithm):
    _config_class = PPOConfig

    def build_learner(self) -> None:
        cfg = self.algo_config
        chain = []
        if cfg.grad_clip:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.optimizer = optax.chain(*chain)
        self.opt_state = self.optimizer.init(self.params)
        self.workers = None
        self._in_graph = is_jax_env(self.env)
        self._axis_name = None
        if self._in_graph and cfg.num_rollout_workers == 0:
            self.sampler = InGraphSampler(
                self.env, self.module, cfg.num_envs_per_worker,
                cfg.rollout_fragment_length)
            self._carry = self.sampler.init_state(self.next_key())
            n = int(cfg.num_learner_devices or 0)
            if n > 1:
                from jax.sharding import Mesh, PartitionSpec as P
                try:
                    from jax import shard_map
                    _rep_kw = {"check_vma": False}
                except ImportError:      # pre-0.8 jax, old signature
                    from jax.experimental.shard_map import shard_map
                    _rep_kw = {"check_rep": False}
                if cfg.num_envs_per_worker % n:
                    raise ValueError(
                        f"num_envs_per_worker={cfg.num_envs_per_worker} "
                        f"must divide over num_learner_devices={n}")
                devices = np.array(jax.devices()[:n])
                if len(devices) < n:
                    raise ValueError(
                        f"num_learner_devices={n} but only "
                        f"{len(devices)} devices visible")
                self._mesh = Mesh(devices, ("data",))
                self._axis_name = "data"
                fn = shard_map(
                    self._fused_iteration, mesh=self._mesh,
                    in_specs=(P(), P(), P("data"), P()),
                    out_specs=(P(), P(), P("data"), P(),
                               P(None, "data")),
                    **_rep_kw)
                self._train_fn = jax.jit(fn)
            else:
                self._train_fn = jax.jit(self._fused_iteration)
        else:
            env_spec, env_cfg = cfg.env, dict(cfg.env_config)
            model_cfg = dict(cfg.model)
            from ray_tpu.rllib.core.rl_module import RLModule
            from ray_tpu.rllib.env.jax_env import make_env

            def env_creator(worker_index, _spec=env_spec, _cfg=env_cfg):
                return make_env(_spec, _cfg)

            def module_creator(env, _mc=model_cfg):
                return RLModule(env.observation_space, env.action_space, _mc)

            self.workers = WorkerSet(
                max(1, cfg.num_rollout_workers), env_creator,
                module_creator, cfg.rollout_fragment_length,
                seed=cfg.seed,
                num_cpus_per_worker=cfg.num_cpus_per_worker,
                connectors=cfg.connector_dict())
            self._update_fn = jax.jit(self._sgd_epochs)

    # -- fully-compiled iteration (JaxEnv path) ---------------------------

    def _fused_iteration(self, params, opt_state, carry, key):
        cfg = self.algo_config
        if self._axis_name:
            # distinct sampling/shuffle streams per shard; params stay
            # replicated because gradients are pmean'd before the update
            key = jax.random.fold_in(
                key, jax.lax.axis_index(self._axis_name))
        k_sample, k_sgd = jax.random.split(key)
        carry, traj, last_value = self.sampler._unroll_impl(
            params, carry, k_sample)
        advs = _gae_scan(traj[sb.REWARDS], traj[sb.VF_PREDS],
                         traj[sb.DONES], last_value, cfg.gamma, cfg.lambda_)
        targets = advs + traj[sb.VF_PREDS]
        flat = {k: v.reshape((-1,) + v.shape[2:])
                for k, v in traj.items()
                if k not in ("episode_return", "episode_len")}
        flat[sb.ADVANTAGES] = advs.reshape(-1)
        flat[sb.VALUE_TARGETS] = targets.reshape(-1)
        params, opt_state, stats = self._sgd_epochs(
            params, opt_state, flat, k_sgd)
        ep = {"episode_return": traj["episode_return"],
              "episode_len": traj["episode_len"]}
        return params, opt_state, carry, stats, ep

    def _sgd_epochs(self, params, opt_state, flat, key):
        """num_sgd_iter epochs of shuffled minibatch SGD as nested scans."""
        cfg = self.algo_config
        n = flat[sb.ADVANTAGES].shape[0]
        mb = min(cfg.sgd_minibatch_size, n)
        num_mb = max(n // mb, 1)
        # advantage standardization (reference: postprocessing.py) —
        # with GLOBAL moments when sharded over the learner mesh
        adv = flat[sb.ADVANTAGES]
        flat = dict(flat)
        if self._axis_name:
            mean = jax.lax.pmean(adv.mean(), self._axis_name)
            var = jax.lax.pmean(jnp.square(adv - mean).mean(),
                                self._axis_name)
            std = jnp.sqrt(var)
        else:
            mean, std = adv.mean(), adv.std()
        flat[sb.ADVANTAGES] = (adv - mean) / (std + 1e-8)

        loss_fn = functools.partial(
            _ppo_loss, self.module,
            clip_param=cfg.clip_param, vf_clip_param=cfg.vf_clip_param,
            vf_loss_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff)

        def one_minibatch(state, batch):
            params, opt_state = state
            (_, stats), grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch), has_aux=True)(params)
            if self._axis_name:
                # DP gradient sync: one pmean over the mesh's data axis
                # (ICI collective on real chips — SURVEY.md §2.3 mapping)
                grads = jax.lax.pmean(grads, self._axis_name)
                stats = jax.lax.pmean(stats, self._axis_name)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), stats

        def one_epoch(state, epoch_key):
            perm = jax.random.permutation(epoch_key, n)
            shuffled = jax.tree.map(
                lambda v: v[perm][:num_mb * mb].reshape(
                    (num_mb, mb) + v.shape[1:]), flat)
            state, stats = jax.lax.scan(one_minibatch, state, shuffled)
            return state, jax.tree.map(jnp.mean, stats)

        epoch_keys = jax.random.split(key, cfg.num_sgd_iter)
        (params, opt_state), stats = jax.lax.scan(
            one_epoch, (params, opt_state), epoch_keys)
        return params, opt_state, jax.tree.map(jnp.mean, stats)

    # -- training step ----------------------------------------------------

    def training_step(self) -> dict:
        if self.workers is None:
            self.params, self.opt_state, self._carry, stats, ep = \
                self._train_fn(self.params, self.opt_state, self._carry,
                               self.next_key())
            metrics = episode_stats(ep)
        else:
            batches, last_values, stats_list = self.workers.sample_all(
                self.params)
            cfg = self.algo_config
            processed = []
            for batch, last_v in zip(batches, last_values):
                batch.update(compute_gae(
                    batch[sb.REWARDS], batch[sb.VF_PREDS],
                    batch[sb.DONES], last_v, cfg.gamma, cfg.lambda_))
                processed.append(batch)
            train_batch = concat_samples(processed)
            device_batch = {k: jnp.asarray(v)
                            for k, v in train_batch.items()}
            self.params, self.opt_state, stats = self._update_fn(
                self.params, self.opt_state, device_batch, self.next_key())
            metrics = merge_episode_stats(stats_list)
        metrics.update({k: float(np.asarray(v))
                        for k, v in stats.items()})
        metrics["num_env_steps_sampled_this_iter"] = (
            self.algo_config.rollout_fragment_length
            * max(self.algo_config.num_envs_per_worker, 1)
            if self.workers is None else
            self.algo_config.rollout_fragment_length
            * max(self.algo_config.num_rollout_workers, 1))
        return metrics

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("PPO", PPO)
