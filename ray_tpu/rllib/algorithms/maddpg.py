"""MADDPG — multi-agent DDPG with centralized critics (Lowe et al. 2017).

Counterpart of the reference's `rllib/algorithms/maddpg/maddpg.py`:
decentralized actors π_i(o_i) act from LOCAL observations; per-agent
critics Q_i(s, a_1..a_n) train on the GLOBAL state and joint action
(centralized training, decentralized execution). Discrete actions use
Gumbel-softmax relaxation for the actor gradient through the critic,
the standard discrete-MADDPG treatment (and what the reference's
contrib implementation does via a softmax action space).

TPU-first shape, like QMIX: the joint rollout is one compiled
vmap+scan; joint transitions replay host-side; the critic and actor
updates are two jitted passes over [B, ...] batches (separate optimizers
so actor gradients never touch critic weights and vice versa).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.multi_agent import is_multi_agent_env
from ray_tpu.rllib.env.spaces import Discrete
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class _Actor(nn.Module):
    n_actions: int
    hiddens: tuple = (64,)

    @nn.compact
    def __call__(self, obs):
        x = obs.reshape(*obs.shape[:-1], -1) if obs.ndim > 2 else obs
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.n_actions)(x)     # logits


class _CentralCritic(nn.Module):
    hiddens: tuple = (128, 64)

    @nn.compact
    def __call__(self, global_obs, joint_actions):
        x = jnp.concatenate([global_obs, joint_actions], axis=-1)
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(1)(x)[..., 0]


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MADDPG)
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.01                   # soft target update
        self.gumbel_temperature = 1.0
        self.train_batch_size = 256
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.n_updates_per_iter = 16
        self.rollout_fragment_length = 16
        self.num_envs_per_worker = 32
        self.actor_hiddens = (64,)
        self.critic_hiddens = (128, 64)


class MADDPG(Algorithm):
    _config_class = MADDPGConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_multi_agent_env(self.env):
            raise ValueError("MADDPG requires a MultiAgentJaxEnv")
        self.agent_ids = tuple(self.env.agent_ids)
        for aid in self.agent_ids:
            if not isinstance(self.env.action_space(aid), Discrete):
                raise ValueError(
                    "this MADDPG implements the discrete (Gumbel-"
                    "softmax) variant; continuous multi-agent control "
                    "is DDPG/TD3 per agent")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.n_actions = {aid: self.env.action_space(aid).n
                          for aid in self.agent_ids}
        obs_dims = {aid: int(np.prod(self.env.observation_space(aid).shape))
                    for aid in self.agent_ids}
        global_dim = sum(obs_dims.values())
        joint_act_dim = sum(self.n_actions.values())

        self.actors = {aid: _Actor(self.n_actions[aid],
                                   tuple(cfg.actor_hiddens))
                       for aid in self.agent_ids}
        self.critics = {aid: _CentralCritic(tuple(cfg.critic_hiddens))
                        for aid in self.agent_ids}
        self.params = {
            "actors": {aid: self.actors[aid].init(
                self.next_key(), jnp.zeros((1, obs_dims[aid])))["params"]
                for aid in self.agent_ids},
            "critics": {aid: self.critics[aid].init(
                self.next_key(), jnp.zeros((1, global_dim)),
                jnp.zeros((1, joint_act_dim)))["params"]
                for aid in self.agent_ids},
        }
        self.build_learner()

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.actor_opt_state = self.actor_opt.init(self.params["actors"])
        self.critic_opt_state = self.critic_opt.init(
            self.params["critics"])
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": jnp.zeros(cfg.num_envs_per_worker),
                       "ep_len": jnp.zeros(cfg.num_envs_per_worker,
                                           jnp.int32)}
        self._sample_fn = jax.jit(self._unroll)
        self._update_fn = jax.jit(self._maddpg_update)
        self._steps_sampled = 0
        self._num_updates = 0
        self._ep_returns: list = []
        self._ep_lens: list = []

    # -- compiled joint rollout (stochastic softmax exploration) ----------

    def _logits(self, actor_params, aid, obs):
        return self.actors[aid].apply({"params": actor_params[aid]},
                                      obs.reshape(obs.shape[0], -1))

    def _unroll(self, params, carry, key):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions = {}
            akeys = jax.random.split(k_act, len(self.agent_ids))
            for i, aid in enumerate(self.agent_ids):
                logits = self._logits(params["actors"], aid, obs[aid])
                actions[aid] = jax.random.categorical(akeys[i], logits)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, rewards, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            team_r = rewards[self.agent_ids[0]]
            ep_ret = carry["ep_ret"] + team_r
            ep_len = carry["ep_len"] + 1
            out = {"obs": obs, "actions": actions, "next_obs": next_obs,
                   "rewards": {a: rewards[a] for a in self.agent_ids},
                   "done": done,
                   "episode_return": jnp.where(done, ep_ret, jnp.nan),
                   "episode_len": jnp.where(done, ep_len, -1)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret),
                         "ep_len": jnp.where(done, 0, ep_len)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        return jax.lax.scan(one_step, carry, keys)

    # -- compiled update ---------------------------------------------------

    def _flat_obs(self, obs, aid):
        return obs[aid].reshape(obs[aid].shape[0], -1)

    def _global_obs(self, obs):
        return jnp.concatenate(
            [self._flat_obs(obs, a) for a in self.agent_ids], axis=-1)

    def _joint_onehot(self, actions):
        return jnp.concatenate(
            [jax.nn.one_hot(actions[a], self.n_actions[a])
             for a in self.agent_ids], axis=-1)

    def _maddpg_update(self, params, target_params, actor_opt_state,
                       critic_opt_state, batch, key):
        cfg = self.algo_config
        obs = {a: batch[f"obs_{a}"] for a in self.agent_ids}
        next_obs = {a: batch[f"next_obs_{a}"] for a in self.agent_ids}
        acts = {a: batch[f"act_{a}"].astype(jnp.int32)
                for a in self.agent_ids}
        g_obs = self._global_obs(obs)
        g_next = self._global_obs(next_obs)
        joint_a = self._joint_onehot(acts)
        nonterm = 1.0 - batch["done"].astype(jnp.float32)

        # target joint action: greedy one-hot from the TARGET actors
        target_joint = jnp.concatenate([
            jax.nn.one_hot(
                jnp.argmax(self._logits(target_params["actors"], a,
                                        next_obs[a]), axis=-1),
                self.n_actions[a])
            for a in self.agent_ids], axis=-1)

        # -- critics: per-agent TD on its own reward stream
        def critic_loss(critic_params):
            losses = []
            for a in self.agent_ids:
                y = batch[f"rew_{a}"] + cfg.gamma * nonterm * \
                    jax.lax.stop_gradient(self.critics[a].apply(
                        {"params": target_params["critics"][a]},
                        g_next, target_joint))
                q = self.critics[a].apply(
                    {"params": critic_params[a]}, g_obs, joint_a)
                losses.append(jnp.mean(jnp.square(q - y)))
            return sum(losses), losses

        (c_loss, per_critic), c_grads = jax.value_and_grad(
            critic_loss, has_aux=True)(params["critics"])
        c_updates, critic_opt_state = self.critic_opt.update(
            c_grads, critic_opt_state, params["critics"])
        new_critics = optax.apply_updates(params["critics"], c_updates)

        # -- actors: maximize Q_i with agent i's action replaced by a
        # Gumbel-softmax relaxed sample (others keep their logged
        # actions); gradient stops at the (already-updated) critics
        gkeys = jax.random.split(key, len(self.agent_ids))

        def actor_loss(actor_params):
            losses = []
            for i, a in enumerate(self.agent_ids):
                logits = self._logits(actor_params, a, obs[a])
                g = jax.random.gumbel(gkeys[i], logits.shape)
                relaxed = jax.nn.softmax(
                    (logits + g) / cfg.gumbel_temperature)
                parts = []
                for b in self.agent_ids:
                    parts.append(relaxed if b == a else
                                 jax.nn.one_hot(acts[b],
                                                self.n_actions[b]))
                q = self.critics[a].apply(
                    {"params": jax.lax.stop_gradient(new_critics[a])},
                    g_obs, jnp.concatenate(parts, axis=-1))
                losses.append(-jnp.mean(q))
            return sum(losses), losses

        (a_loss, per_actor), a_grads = jax.value_and_grad(
            actor_loss, has_aux=True)(params["actors"])
        a_updates, actor_opt_state = self.actor_opt.update(
            a_grads, actor_opt_state, params["actors"])
        new_actors = optax.apply_updates(params["actors"], a_updates)

        new_params = {"actors": new_actors, "critics": new_critics}
        # soft target update (DDPG-style polyak)
        new_targets = jax.tree.map(
            lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
            target_params, new_params)
        return (new_params, new_targets, actor_opt_state,
                critic_opt_state,
                {"critic_loss": c_loss, "actor_loss": a_loss})

    # -- training loop -----------------------------------------------------

    def training_step(self) -> dict:
        cfg = self.algo_config
        self._carry, traj = self._sample_fn(self.params, self._carry,
                                            self.next_key())
        host = {k: np.asarray(v) for k, v in traj.items()
                if k in ("done",)}
        flat = {"done": host["done"].reshape(-1)}
        for a in self.agent_ids:
            for src, dst in (("obs", "obs"), ("next_obs", "next_obs"),
                             ("actions", "act"), ("rewards", "rew")):
                v = np.asarray(traj[src][a])
                flat[f"{dst}_{a}"] = v.reshape((-1,) + v.shape[2:])
        self.buffer.add_batch(flat)
        n = len(flat["done"])
        self._steps_sampled += n
        rets = np.asarray(traj["episode_return"]).ravel()
        lens = np.asarray(traj["episode_len"]).ravel()
        fin = ~np.isnan(rets)
        self._ep_returns.extend(rets[fin].tolist())
        self._ep_lens.extend(lens[fin & (lens >= 0)].tolist())
        self._ep_returns = self._ep_returns[-200:]
        self._ep_lens = self._ep_lens[-200:]

        stats = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = {k: jnp.asarray(v) for k, v in
                         self.buffer.sample(cfg.train_batch_size).items()}
                (self.params, self.target_params, self.actor_opt_state,
                 self.critic_opt_state, stats) = self._update_fn(
                    self.params, self.target_params,
                    self.actor_opt_state, self.critic_opt_state,
                    batch, self.next_key())
                self._num_updates += 1
        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._ep_lens))
                                 if self._ep_lens else float("nan")),
            "episodes_this_iter": int(fin.sum()),
            "num_env_steps_sampled": self._steps_sampled,
            "num_updates": self._num_updates,
            **{k: float(np.asarray(v)) for k, v in stats.items()},
        }

    def compute_joint_action(self, obs: dict) -> dict:
        """Greedy decentralized execution (each actor sees only its own
        observation)."""
        out = {}
        for a in self.agent_ids:
            logits = self._logits(
                self.params["actors"], a,
                jnp.asarray(obs[a], jnp.float32)[None])
            out[a] = int(jnp.argmax(logits, axis=-1)[0])
        return out

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]


register_algorithm("MADDPG", MADDPG)
