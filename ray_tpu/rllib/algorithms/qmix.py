"""QMIX — cooperative multi-agent Q-learning with monotonic value
factorization (Rashid et al. 2018).

Counterpart of the reference's `rllib/algorithms/qmix/qmix.py` +
`qmix_policy.py`/`model.py`: per-agent Q-networks whose chosen Qs are
mixed into Q_tot by a hypernetwork-conditioned MONOTONIC mixer (weights
forced positive via abs), trained end-to-end with a TD target on the
SHARED team reward. Monotonicity means each agent's greedy argmax over
its own Q is the team-optimal joint action — centralized training,
decentralized execution.

TPU-first shape: the multi-agent rollout is one compiled scan
(per-agent epsilon-greedy inline, fixed agent set = pytree structure),
joint transitions replay host-side, and the QMIX update — agent nets +
hypernet mixer + double-Q targets — is a single jitted function over
[B, ...] batches.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.core.rl_module import QModule
from ray_tpu.rllib.env.multi_agent import is_multi_agent_env
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class _MonotonicMixer(nn.Module):
    """Q_tot(s, q_1..q_n): hypernetworks map the global state to
    POSITIVE mixing weights (abs), so dQ_tot/dq_i >= 0 — the QMIX
    monotonicity constraint (reference: qmix/model.py QMixer)."""
    n_agents: int
    embed: int = 32

    @nn.compact
    def __call__(self, state, agent_qs):
        # agent_qs: [B, n_agents]; state: [B, state_dim]
        w1 = jnp.abs(nn.Dense(self.n_agents * self.embed)(state))
        w1 = w1.reshape(-1, self.n_agents, self.embed)
        b1 = nn.Dense(self.embed)(state)
        hidden = nn.elu(
            jnp.einsum("ba,bae->be", agent_qs, w1) + b1)
        w2 = jnp.abs(nn.Dense(self.embed)(state))
        b2 = nn.Dense(1)(nn.relu(nn.Dense(self.embed)(state)))
        q_tot = jnp.einsum("be,be->b", hidden, w2) + b2[:, 0]
        return q_tot


class QMIXConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or QMIX)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.target_network_update_freq = 200   # gradient updates
        self.double_q = True
        self.mixing_embed_dim = 32
        self.n_updates_per_iter = 32
        self.rollout_fragment_length = 16
        self.num_envs_per_worker = 32
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 20_000
        self.model = {"fcnet_hiddens": (64,), "fcnet_activation": "relu"}


class QMIX(Algorithm):
    _config_class = QMIXConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_multi_agent_env(self.env):
            raise ValueError("QMIX requires a MultiAgentJaxEnv "
                             "(cooperative, shared reward)")
        self.agent_ids = tuple(self.env.agent_ids)
        self._rng = jax.random.PRNGKey(cfg.seed)
        # one Q-module per agent (the reference shares parameters via
        # agent one-hot; separate nets are the general case and the
        # fixed agent set keeps it one compiled program either way)
        self.modules = {
            aid: QModule(self.env.observation_space(aid),
                         self.env.action_space(aid), dict(cfg.model))
            for aid in self.agent_ids}
        self.params = {aid: m.init(self.next_key())
                       for aid, m in self.modules.items()}
        state_dim = sum(
            int(np.prod(self.env.observation_space(a).shape))
            for a in self.agent_ids)
        self.mixer = _MonotonicMixer(len(self.agent_ids),
                                     cfg.mixing_embed_dim)
        self.params["__mixer__"] = self.mixer.init(
            self.next_key(), jnp.zeros((1, state_dim)),
            jnp.zeros((1, len(self.agent_ids))))["params"]
        self.build_learner()

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": jnp.zeros(cfg.num_envs_per_worker),
                       "ep_len": jnp.zeros(cfg.num_envs_per_worker,
                                           jnp.int32)}
        self._sample_fn = jax.jit(self._unroll)
        self._update_fn = jax.jit(self._qmix_update)
        self._steps_sampled = 0
        self._num_updates = 0
        self._last_target_update = 0
        self._ep_returns: list = []
        self._ep_lens: list = []

    # -- compiled joint rollout -------------------------------------------

    def _unroll(self, params, carry, key, epsilon):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions = {}
            akeys = jax.random.split(k_act, len(self.agent_ids))
            for i, aid in enumerate(self.agent_ids):
                a, _, _ = self.modules[aid].compute_actions(
                    params[aid], obs[aid], akeys[i], epsilon=epsilon)
                actions[aid] = a
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, rewards, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            # cooperative: the TEAM reward is the (identical) shared
            # scalar; use the first agent's stream
            team_r = rewards[self.agent_ids[0]]
            ep_ret = carry["ep_ret"] + team_r
            ep_len = carry["ep_len"] + 1
            out = {"obs": obs, "actions": actions,
                   "next_obs": next_obs, "reward": team_r,
                   "done": done,
                   "episode_return": jnp.where(done, ep_ret, jnp.nan),
                   "episode_len": jnp.where(done, ep_len, -1)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret),
                         "ep_len": jnp.where(done, 0, ep_len)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        return jax.lax.scan(one_step, carry, keys)

    # -- compiled QMIX update ---------------------------------------------

    def _global_state(self, obs):
        return jnp.concatenate(
            [obs[a].reshape(obs[a].shape[0], -1)
             for a in self.agent_ids], axis=-1)

    def _q_tot(self, params, obs, actions):
        qs = []
        for aid in self.agent_ids:
            q = self.modules[aid].q_values(params[aid], obs[aid])
            qs.append(jnp.take_along_axis(
                q, actions[aid][..., None].astype(jnp.int32),
                axis=-1)[..., 0])
        agent_qs = jnp.stack(qs, axis=-1)
        return self.mixer.apply({"params": params["__mixer__"]},
                                self._global_state(obs), agent_qs)

    def _greedy_joint(self, params, obs):
        return {aid: jnp.argmax(
            self.modules[aid].q_values(params[aid], obs[aid]), axis=-1)
            for aid in self.agent_ids}

    def _qmix_update(self, params, target_params, opt_state, batch):
        cfg = self.algo_config
        obs = {a: batch[f"obs_{a}"] for a in self.agent_ids}
        next_obs = {a: batch[f"next_obs_{a}"] for a in self.agent_ids}
        actions = {a: batch[f"act_{a}"] for a in self.agent_ids}

        # decentralized greedy argmax (monotonicity makes it the joint
        # argmax of Q_tot); double-Q: argmax under ONLINE params, value
        # under TARGET params
        sel_params = params if cfg.double_q else target_params
        next_acts = self._greedy_joint(sel_params, next_obs)
        q_tot_next = self._q_tot(target_params, next_obs, next_acts)
        nonterm = 1.0 - batch["done"].astype(jnp.float32)
        target = batch["reward"] + cfg.gamma * nonterm * \
            jax.lax.stop_gradient(q_tot_next)

        def loss_fn(p):
            q_tot = self._q_tot(p, obs, actions)
            return jnp.mean(optax.huber_loss(q_tot, target))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # ---------------------------------------------------------------------

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0,
                   self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> dict:
        cfg = self.algo_config
        losses = []
        self._carry, traj = self._sample_fn(
            self.params, self._carry, self.next_key(),
            jnp.asarray(self._epsilon()))
        host = jax.tree.map(np.asarray, traj)
        rets = host.pop("episode_return").ravel()
        lens = host.pop("episode_len").ravel()
        fin = ~np.isnan(rets)
        self._ep_returns.extend(rets[fin].tolist())
        self._ep_lens.extend(lens[fin & (lens >= 0)].tolist())
        self._ep_returns = self._ep_returns[-100:]
        self._ep_lens = self._ep_lens[-100:]
        flat = {"reward": host["reward"].reshape(-1),
                "done": host["done"].reshape(-1)}
        for a in self.agent_ids:
            for src, dst in (("obs", "obs"), ("next_obs", "next_obs"),
                             ("actions", "act")):
                v = host[src][a]
                flat[f"{dst}_{a}"] = v.reshape((-1,) + v.shape[2:])
        self.buffer.add_batch(flat)
        self._steps_sampled += len(flat["reward"])

        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                device_batch = {k: jnp.asarray(v)
                                for k, v in batch.items()}
                self.params, self.opt_state, loss = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    device_batch)
                losses.append(float(loss))
                self._num_updates += 1
                if (self._num_updates - self._last_target_update
                        >= cfg.target_network_update_freq):
                    self.target_params = jax.tree.map(
                        jnp.copy, self.params)
                    self._last_target_update = self._num_updates

        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._ep_lens))
                                 if self._ep_lens else float("nan")),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params,
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]


register_algorithm("QMIX", QMIX)
