"""MARWIL + BC — offline policy learning from logged experience.

Counterpart of the reference's `rllib/algorithms/marwil/` (marwil.py
config with `beta`; loss `marwil_torch_policy.py`: advantage-weighted
behavioral cloning, exp(beta * A / c) * -logp, with a moving estimate c of
the advantage scale) and `rllib/algorithms/bc/` (BC = MARWIL with beta=0,
bc.py). Data comes from `ray_tpu.rllib.offline.JsonReader` shards written
by a behaviour policy; advantages are Monte-Carlo returns minus the
learned value baseline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.jax_env import make_env
from ray_tpu.rllib.offline import resolve_input


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta = 1.0                 # 0 => plain behavioral cloning
        self.input_ = None              # path to offline shards (required)
        self.lr = 1e-3
        self.train_batch_size = 1024
        self.vf_coeff = 1.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-7
        self.n_updates_per_iter = 16

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self


class MARWIL(Algorithm):
    _config_class = MARWILConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if not cfg.input_:
            raise ValueError("MARWIL/BC require config.offline_data("
                             "input_=<shard dir>)")
        # env used only for spaces (reference MARWIL also builds the env
        # for spaces + optional evaluation)
        self.env = make_env(cfg.env, cfg.env_config)
        self.module = RLModule(self.env.observation_space,
                               self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.params = self.module.init(self.next_key())
        self.reader = resolve_input(cfg.input_)
        self._data = self._postprocess(self.reader.read_all())
        self.build_learner()

    def _postprocess(self, batch) -> dict:
        """Monte-Carlo returns per episode (reference:
        postprocessing.compute_advantages with use_gae=False)."""
        from ray_tpu.rllib.offline import _per_episode
        cfg = self.algo_config
        returns = []
        for ep in _per_episode(batch):
            r = np.asarray(ep[sb.REWARDS], dtype=np.float32)
            g = np.zeros_like(r)
            acc = 0.0
            for i in range(len(r) - 1, -1, -1):
                acc = r[i] + cfg.gamma * acc
                g[i] = acc
            returns.append(g)
        out = {k: np.asarray(v) for k, v in batch.items()}
        out["mc_returns"] = np.concatenate(returns)
        return out

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        # moving estimate of squared advantage norm (the reference's
        # update_averaged_sqd_adv_norm)
        self._adv_norm = jnp.asarray(1.0)
        self._update_fn = jax.jit(self._marwil_update)
        self._np_rng = np.random.default_rng(cfg.seed)

    def _marwil_update(self, params, opt_state, adv_norm, batch):
        cfg = self.algo_config

        def loss_fn(p):
            dist, values = self.module.forward(p, batch[sb.OBS])
            logp = dist.logp(batch[sb.ACTIONS])
            adv = batch["mc_returns"] - values
            vf_loss = jnp.mean(jnp.square(adv))
            if cfg.beta > 0:
                scaled = adv / jnp.sqrt(adv_norm + 1e-8)
                weights = jnp.exp(jnp.clip(cfg.beta *
                                           jax.lax.stop_gradient(scaled),
                                           -20.0, 2.0))
            else:
                weights = jnp.ones_like(logp)
            policy_loss = -jnp.mean(weights * logp)
            total = policy_loss + cfg.vf_coeff * vf_loss * \
                (1.0 if cfg.beta > 0 else 0.0)
            return total, (policy_loss, vf_loss,
                           jnp.mean(jnp.square(adv)))

        (loss, (pl, vl, sq)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        rate = cfg.moving_average_sqd_adv_norm_update_rate
        adv_norm = adv_norm + rate * (sq - adv_norm)
        return params, opt_state, adv_norm, loss, pl, vl

    def training_step(self) -> dict:
        cfg = self.algo_config
        n = len(self._data[sb.REWARDS])
        losses, pls, vls = [], [], []
        for _ in range(cfg.n_updates_per_iter):
            idx = self._np_rng.integers(0, n,
                                        min(cfg.train_batch_size, n))
            batch = {k: jnp.asarray(v[idx]) for k, v in self._data.items()
                     if k in (sb.OBS, sb.ACTIONS, "mc_returns")}
            (self.params, self.opt_state, self._adv_norm, loss, pl,
             vl) = self._update_fn(self.params, self.opt_state,
                                   self._adv_norm, batch)
            losses.append(float(loss))
            pls.append(float(pl))
            vls.append(float(vl))
        return {"loss": float(np.mean(losses)),
                "policy_loss": float(np.mean(pls)),
                "vf_loss": float(np.mean(vls)),
                "num_samples": n}

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state,
                "adv_norm": self._adv_norm}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self._adv_norm = state["adv_norm"]


class BCConfig(MARWILConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.beta = 0.0


class BC(MARWIL):
    """Behavioral cloning = MARWIL at beta 0 (reference: bc.py)."""
    _config_class = BCConfig


register_algorithm("MARWIL", MARWIL)
register_algorithm("BC", BC)
