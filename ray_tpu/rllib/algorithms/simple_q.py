"""SimpleQ and A3C — the reference's remaining registry entries, as
presets over the engines that subsume them.

- SimpleQ (reference: `rllib/algorithms/simple_q/simple_q.py`) is DQN
  minus the extensions: no double-Q, no prioritized replay, plain
  target-network sync. Here it is DQN with those switches off.
- A3C (reference: `rllib/algorithms/a3c/a3c.py`, deprecated upstream in
  favor of its synchronous form) is the A3C loss with ASYNCHRONOUS
  actor-side sampling. A2C already runs the A3C loss; the async path
  with stale-gradient tolerance is IMPALA's architecture with V-trace
  correcting the lag — so A3C maps to A2C over rollout-worker actors
  (workers sample with slightly stale weights, the exact A3C regime).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SimpleQ)
        self.double_q = False
        self.prioritized_replay = False
        # SimpleQ presets assume a small update budget (it is the "quick
        # baseline" entry). Without double-Q and with sparse target syncs,
        # 1-step backups propagate value one sync at a time and the Q
        # ranking never separates; a 5-step backup + a hotter lr and a
        # faster epsilon decay make the small budget sufficient.
        self.n_step = 5
        self.lr = 3e-3
        self.epsilon_timesteps = 8000


class SimpleQ(DQN):
    _config_class = SimpleQConfig


class A3CConfig(A2CConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A3C)
        # asynchronous flavor: decoupled rollout actors sampling with
        # the weights they last received
        self.num_rollout_workers = 2


class A3C(A2C):
    _config_class = A3CConfig


register_algorithm("SimpleQ", SimpleQ)
register_algorithm("A3C", A3C)
