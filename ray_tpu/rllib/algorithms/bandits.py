"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Counterpart of the reference's `rllib/algorithms/bandit/` (LinUCB /
LinTS over `bandit_torch_model.py` discrete-action linear models). The
TPU-native rewrite keeps the per-arm ridge-regression sufficient
statistics as jnp arrays and performs the rank-1 updates + arm scoring
as one jitted function over a batch of contexts — the Sherman-Morrison
A^-1 update replaces the reference's per-step torch solve.

Envs: any JaxEnv whose episodes are one step (context -> arm ->
reward), e.g. `LinearBanditEnv` below.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import JaxEnv, is_jax_env, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete


class LinearBanditEnv(JaxEnv):
    """Synthetic contextual bandit: reward = <theta_arm, context> +
    noise. One-step episodes (the bandit setting)."""

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.dim = int(cfg.get("dim", 8))
        self.num_arms = int(cfg.get("num_arms", 4))
        self.noise = float(cfg.get("noise", 0.1))
        key = jax.random.PRNGKey(int(cfg.get("problem_seed", 7)))
        self.theta = jax.random.normal(key, (self.num_arms, self.dim))
        self.theta = self.theta / jnp.linalg.norm(
            self.theta, axis=1, keepdims=True)
        self.observation_space = Box(-jnp.inf, jnp.inf, (self.dim,))
        self.action_space = Discrete(self.num_arms)

    def reset(self, key):
        ctx = jax.random.normal(key, (self.dim,))
        ctx = ctx / jnp.linalg.norm(ctx)
        return {"ctx": ctx}, ctx

    def best_reward(self, ctx):
        return jnp.max(self.theta @ ctx)

    def step(self, state, action, key):
        ctx = state["ctx"]
        mean = self.theta[action] @ ctx
        reward = mean + self.noise * jax.random.normal(key)
        new_state, new_obs = self.reset(key)
        return new_state, new_obs, reward, jnp.asarray(True), {}


register_env("LinearBandit", lambda cfg: LinearBanditEnv(cfg))


class BanditConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class)
        self.alpha = 1.0            # LinUCB exploration width / TS scale
        self.lambda_reg = 1.0       # ridge prior
        self.steps_per_iter = 256


class LinUCBConfig(BanditConfig):
    def __init__(self):
        super().__init__(LinUCB)


class LinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__(LinTS)


class _LinearBandit(Algorithm):
    """Shared LinUCB/LinTS machinery: per-arm (A^-1, b) ridge stats,
    rank-1 Sherman-Morrison updates, jitted interact-update loop."""

    thompson = False

    def setup(self, config: dict) -> None:
        super().setup(config)
        if not is_jax_env(self.env):
            raise ValueError(
                "linear bandits need a JaxEnv (the interact loop is "
                "jitted); wrap python envs")
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("bandits need a Discrete action space")

    def build_learner(self) -> None:
        cfg = self.algo_config
        dim = int(np.prod(self.env.observation_space.shape))
        arms = self.env.action_space.n
        self._a_inv = jnp.stack(
            [jnp.eye(dim) / cfg.lambda_reg for _ in range(arms)])
        self._b = jnp.zeros((arms, dim))
        self._steps = 0
        self._loop = jax.jit(self._interact_loop)
        self._reward_hist: list = []
        self._regret_hist: list = []

    def _scores(self, a_inv, b, ctx, key):
        theta_hat = jnp.einsum("aij,aj->ai", a_inv, b)
        mean = theta_hat @ ctx
        var = jnp.einsum("i,aij,j->a", ctx, a_inv, ctx)
        if self.thompson:
            # LinTS: sample from the per-arm posterior
            noise = jax.random.normal(key, mean.shape)
            return mean + self.algo_config.alpha * jnp.sqrt(var) * noise
        return mean + self.algo_config.alpha * jnp.sqrt(var)

    def _interact_loop(self, a_inv, b, key):
        env = self.env

        def one(carry, k):
            a_inv, b = carry
            k_ctx, k_score, k_rew = jax.random.split(k, 3)
            _, ctx = env.reset(k_ctx)
            arm = jnp.argmax(self._scores(a_inv, b, ctx, k_score))
            _, _, reward, _, _ = env.step({"ctx": ctx}, arm, k_rew)
            # Sherman-Morrison rank-1 update of the chosen arm's A^-1
            ai = a_inv[arm]
            v = ai @ ctx
            ai = ai - jnp.outer(v, v) / (1.0 + ctx @ v)
            a_inv2 = a_inv.at[arm].set(ai)
            b2 = b.at[arm].add(reward * ctx)
            regret = env.best_reward(ctx) - env.theta[arm] @ ctx \
                if hasattr(env, "best_reward") else jnp.asarray(0.0)
            return (a_inv2, b2), (reward, regret)

        keys = jax.random.split(key, self.algo_config.steps_per_iter)
        (a_inv, b), (rewards, regrets) = jax.lax.scan(
            one, (a_inv, b), keys)
        return a_inv, b, rewards, regrets

    def training_step(self) -> dict:
        self._a_inv, self._b, rewards, regrets = self._loop(
            self._a_inv, self._b, self.next_key())
        self._steps += self.algo_config.steps_per_iter
        mean_rew = float(jnp.mean(rewards))
        mean_regret = float(jnp.mean(regrets))
        self._reward_hist.append(mean_rew)
        self._regret_hist.append(mean_regret)
        return {
            "episode_reward_mean": mean_rew,
            "mean_regret": mean_regret,
            "num_env_steps_sampled": self._steps,
        }

    def get_state(self) -> dict:
        return {"a_inv": np.asarray(self._a_inv),
                "b": np.asarray(self._b)}

    def set_state(self, state: dict) -> None:
        self._a_inv = jnp.asarray(state["a_inv"])
        self._b = jnp.asarray(state["b"])


class LinUCB(_LinearBandit):
    _config_class = LinUCBConfig
    thompson = False


class LinTS(_LinearBandit):
    _config_class = LinTSConfig
    thompson = True


register_algorithm("LinUCB", LinUCB)
register_algorithm("LinTS", LinTS)
