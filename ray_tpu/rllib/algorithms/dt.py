"""Decision Transformer — offline RL as return-conditioned sequence
modeling (Chen et al. 2021).

Counterpart of the reference's `rllib/algorithms/dt/` (dt.py +
`segmentation_buffer.py` + `dt_torch_model.py`): episodes become
(return-to-go, state, action) token triples, a small causal transformer
is trained to predict the action at each state token, and acting means
conditioning the context on a TARGET return — ask for expert return,
get expert behavior, even when the dataset mixes qualities.

TPU-first shape: window sampling pads to a fixed K so every batch is
one static-shape [B, 3K, D] causal-attention program (the reference's
segmentation buffer does the same padding for its torch GPT); training
is a single jitted update and evaluation's per-step forward is jitted
once. The transformer is plain flax (LN -> causal MHA -> MLP blocks) —
small enough to live here, shaped like models/gpt's blocks.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.spaces import Discrete


class _Block(nn.Module):
    embed: int
    heads: int

    @nn.compact
    def __call__(self, x, mask):
        h = nn.LayerNorm()(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, qkv_features=self.embed)(h, h, mask=mask)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(4 * self.embed)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.embed)(h)
        return x + h


class _DTNet(nn.Module):
    """(rtg, obs, action) triples -> per-state-token action logits."""
    obs_dim: int
    num_actions: int
    embed: int
    heads: int
    layers: int
    max_len: int          # K (timesteps per window)

    @nn.compact
    def __call__(self, rtg, obs, act, timesteps):
        # rtg [B,K,1], obs [B,K,obs_dim], act [B,K] — the TRUE action of
        # each step (-1 where unknown/padded, e.g. the current step at
        # act time): the causal mask already hides a_t from its own
        # prediction at the s_t token, while a_{t-1} in slot t-1 stays
        # visible — the canonical (R, s, a) DT ordering. timesteps [B,K].
        B, K = rtg.shape[0], rtg.shape[1]
        t_emb = nn.Embed(self.max_len + 1, self.embed)(
            jnp.clip(timesteps, 0, self.max_len))
        e_rtg = nn.Dense(self.embed)(rtg) + t_emb
        e_obs = nn.Dense(self.embed)(obs) + t_emb
        a_onehot = jax.nn.one_hot(jnp.clip(act, 0, None),
                                  self.num_actions) * \
            (act >= 0).astype(jnp.float32)[..., None]
        e_act = nn.Dense(self.embed)(a_onehot) + t_emb
        # interleave (rtg_t, s_t, a_t): [B, 3K, D]
        toks = jnp.stack([e_rtg, e_obs, e_act], axis=2).reshape(
            B, 3 * K, self.embed)
        causal = nn.make_causal_mask(jnp.ones((B, 3 * K)))
        x = toks
        for _ in range(self.layers):
            x = _Block(self.embed, self.heads)(x, causal)
        x = nn.LayerNorm()(x)
        # action predicted at each STATE token position (index 3t+1)
        state_tok = x.reshape(B, K, 3, self.embed)[:, :, 1, :]
        return nn.Dense(self.num_actions)(state_tok)     # [B, K, A]


class DTConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DT)
        self.lr = 1e-3
        self.train_batch_size = 64
        self.context_len = 20              # K
        self.embed_dim = 64
        self.n_layers = 2
        self.n_heads = 2
        self.n_updates_per_iter = 50
        self.target_return = None          # None -> best in dataset
        self.eval_episodes = 4
        self.offline_max_batches = 1000    # cap on cycling readers
        # offline data: list of SampleBatch-like dicts, a callable
        # yielding them, or an object with .next() (JsonReader)
        self.input_ = None

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self


class DT(Algorithm):
    _config_class = DTConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("DT v1 supports Discrete action spaces")
        if cfg.input_ is None:
            raise ValueError(
                "DT is an OFFLINE algorithm: pass experience via "
                "config.offline_data(input_=...) (reference: dt.py "
                "requires offline input)")
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.num_actions = self.env.action_space.n
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._episodes = self._segment_episodes(self._drain_input())
        if not self._episodes:
            raise ValueError("offline input contained no complete episodes")
        self._ep_returns = np.asarray(
            [float(ep["rtg"][0]) for ep in self._episodes])
        self.net = _DTNet(self.obs_dim, self.num_actions, cfg.embed_dim,
                          cfg.n_heads, cfg.n_layers,
                          max(len(ep["obs"]) for ep in self._episodes))
        K = cfg.context_len
        self.params = self.net.init(
            self.next_key(), jnp.zeros((1, K, 1)),
            jnp.zeros((1, K, self.obs_dim)),
            jnp.zeros((1, K), jnp.int32), jnp.zeros((1, K), jnp.int32))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = jax.jit(self._update)
        self._act_fn = jax.jit(self.net.apply)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._iter = 0

    # -- offline ingestion -------------------------------------------------

    def _drain_input(self):
        from ray_tpu.rllib.offline import resolve_input
        src = resolve_input(self.algo_config.input_)
        if callable(src):
            batches = []
            out = src()
            batches = list(out) if isinstance(out, (list, tuple)) else [out]
        elif hasattr(src, "next"):
            # BOUNDED drain: this repo's JsonReader.next() cycles over
            # its shards forever (offline.py) and never raises — cap at
            # offline_max_batches so setup() can't spin/OOM
            cap = int(getattr(self.algo_config, "offline_max_batches",
                              1000))
            batches = []
            try:
                for _ in range(cap):
                    batches.append(src.next())
            except StopIteration:
                pass
        else:
            batches = list(src)
        return batches

    def _segment_episodes(self, batches):
        """Concatenate batches, split on dones, attach returns-to-go
        (reference: dt segmentation_buffer.py)."""
        obs = np.concatenate([np.asarray(b[sb.OBS]) for b in batches])
        act = np.concatenate([np.asarray(b[sb.ACTIONS]) for b in batches])
        rew = np.concatenate([np.asarray(b[sb.REWARDS]) for b in batches])
        done = np.concatenate(
            [np.asarray(b[sb.DONES]) for b in batches]).astype(bool)
        episodes, start = [], 0
        for i in range(len(done)):
            if done[i]:
                r = rew[start:i + 1].astype(np.float64)
                rtg = np.cumsum(r[::-1])[::-1]
                episodes.append({
                    "obs": obs[start:i + 1].reshape(i + 1 - start, -1)
                    .astype(np.float32),
                    "act": act[start:i + 1].astype(np.int32),
                    "rtg": rtg.astype(np.float32),
                })
                start = i + 1
        return episodes

    def _sample_windows(self, batch_size):
        cfg = self.algo_config
        K = cfg.context_len
        # episodes weighted by length (every timestep equally likely)
        lens = np.asarray([len(e["act"]) for e in self._episodes])
        p = lens / lens.sum()
        rtg = np.zeros((batch_size, K, 1), np.float32)
        obs = np.zeros((batch_size, K, self.obs_dim), np.float32)
        act = np.full((batch_size, K), -1, np.int32)   # true actions
        tgt = np.zeros((batch_size, K), np.int32)
        ts = np.zeros((batch_size, K), np.int32)
        mask = np.zeros((batch_size, K), np.float32)
        for b in range(batch_size):
            ep = self._episodes[self._np_rng.choice(len(self._episodes),
                                                    p=p)]
            T = len(ep["act"])
            end = int(self._np_rng.integers(1, T + 1))   # window end (excl)
            lo = max(0, end - K)
            n = end - lo
            sl = slice(K - n, K)                          # right-align
            rtg[b, sl, 0] = ep["rtg"][lo:end]
            obs[b, sl] = ep["obs"][lo:end]
            tgt[b, sl] = ep["act"][lo:end]
            act[b, sl] = ep["act"][lo:end]
            ts[b, sl] = np.arange(lo, end)
            mask[b, sl] = 1.0
        return rtg, obs, act, tgt, ts, mask

    # -- training ----------------------------------------------------------

    def _update(self, params, opt_state, rtg, obs, act, tgt, ts, mask):
        def loss_fn(p):
            logits = self.net.apply(p, rtg, obs, act, ts)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, tgt[..., None], axis=-1)[..., 0]
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return optax.apply_updates(params, updates), opt_state, loss

    def training_step(self) -> dict:
        cfg = self.algo_config
        losses = []
        for _ in range(cfg.n_updates_per_iter):
            w = self._sample_windows(cfg.train_batch_size)
            self.params, self.opt_state, loss = self._update_fn(
                self.params, self.opt_state,
                *(jnp.asarray(x) for x in w))
            losses.append(float(loss))
        self._iter += 1
        metrics = {
            "loss": float(np.mean(losses)),
            "num_episodes_offline": len(self._episodes),
            "dataset_return_mean": float(self._ep_returns.mean()),
            "dataset_return_max": float(self._ep_returns.max()),
        }
        if cfg.eval_episodes:
            rews = [self._eval_episode() for _ in range(cfg.eval_episodes)]
            metrics["episode_reward_mean"] = float(np.mean(rews))
        return metrics

    # -- return-conditioned acting -----------------------------------------

    def _eval_episode(self) -> float:
        """Roll one episode conditioning on the target return
        (reference: dt.py evaluation with rtg decay)."""
        cfg = self.algo_config
        K = cfg.context_len
        target = (cfg.target_return if cfg.target_return is not None
                  else float(self._ep_returns.max()))
        from ray_tpu.rllib.env.jax_env import is_jax_env
        env = self.env
        key = self.next_key()
        if is_jax_env(env):
            state, obs0 = env.reset(key)
        else:
            out = env.reset()
            obs0 = out[0] if isinstance(out, tuple) else out
        obs_hist = [np.asarray(obs0, np.float32).reshape(-1)]
        act_hist: list[int] = []
        rtg_hist = [target]
        total, t, done = 0.0, 0, False
        while not done and t < 1000:
            lo = max(0, len(obs_hist) - K)
            window = obs_hist[lo:]
            n = len(window)
            rtg = np.zeros((1, K, 1), np.float32)
            obs = np.zeros((1, K, self.obs_dim), np.float32)
            act = np.full((1, K), -1, np.int32)
            ts = np.zeros((1, K), np.int32)
            sl = slice(K - n, K)
            rtg[0, sl, 0] = rtg_hist[lo:]
            obs[0, sl] = np.stack(window)
            # TRUE actions for the window's past steps; the current
            # step's slot stays -1 (unknown — and causally invisible to
            # its own prediction anyway)
            known = act_hist[lo:]
            if known:
                act[0, K - n:K - n + len(known)] = known
            ts[0, sl] = np.arange(lo, lo + n)
            logits = self._act_fn(self.params, jnp.asarray(rtg),
                                  jnp.asarray(obs), jnp.asarray(act),
                                  jnp.asarray(ts))
            a = int(np.asarray(jnp.argmax(logits[0, K - 1])))
            if is_jax_env(env):
                key, k = jax.random.split(key)
                state, nxt, r, d, _ = env.step(state, jnp.asarray(a), k)
                nxt = np.asarray(nxt)
                r, done = float(r), bool(d)
            else:
                out = env.step(a)
                if len(out) == 5:
                    nxt, r, term, trunc, _ = out
                    done = bool(term or trunc)
                else:
                    nxt, r, done, _ = out
            total += r
            t += 1
            act_hist.append(a)
            obs_hist.append(np.asarray(nxt, np.float32).reshape(-1))
            rtg_hist.append(rtg_hist[-1] - r)
        return total

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("DT", DT)
