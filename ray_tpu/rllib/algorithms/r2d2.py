"""R2D2 — Recurrent Replay Distributed DQN (Kapturowski et al. 2019).

Counterpart of the reference's `rllib/algorithms/r2d2/r2d2.py` +
`r2d2_torch_policy.py`: LSTM Q-network, SEQUENCE replay with the
stored-state strategy, burn-in unroll to refresh stale recurrent state,
double-Q targets, and the paper's eta-mix sequence priority
(eta*max|td| + (1-eta)*mean|td|).

TPU-first shape: sampling is one compiled scan that carries the LSTM
state and emits fixed-length fragments WITH their fragment-start state
(core/recurrent.py) — the replay row IS the scan output, no host-side
rnn_sequencing repacking. Burn-in + train unrolls are a single jitted
update over [B, T, ...] sequences, so the MXU sees batched matmuls,
and the only host work is the sum-tree bookkeeping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.core.recurrent import (
    RecurrentInGraphSampler, RecurrentQModule)
from ray_tpu.rllib.env.jax_env import is_jax_env
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer


class R2D2Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self.lr = 1e-3
        self.train_batch_size = 32          # sequences per update
        self.buffer_size = 4000             # sequences
        self.learning_starts = 200          # sequences
        self.target_network_update_freq = 400   # gradient updates
        self.double_q = True
        # sequence shape: burn_in prefix refreshes the stored state with
        # CURRENT params (no grads), the remainder trains
        self.burn_in = 8
        self.rollout_fragment_length = 40   # burn_in + trained steps
        self.num_envs_per_worker = 32
        self.n_updates_per_iter = 32
        self.priority_eta = 0.9             # paper's eta-mix
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 30_000
        self.model = {"fcnet_hiddens": (64,), "lstm_cell_size": 64}


class R2D2(Algorithm):
    _config_class = R2D2Config

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_jax_env(self.env):
            raise ValueError("R2D2 v1 requires a JaxEnv (the compiled "
                             "recurrent sampler carries LSTM state "
                             "through the scan)")
        if cfg.burn_in >= cfg.rollout_fragment_length:
            raise ValueError("burn_in must be < rollout_fragment_length")
        self.module = RecurrentQModule(self.env.observation_space,
                                       self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, k = jax.random.split(self._rng)
        self.params = self.module.init(k)
        self.build_learner()

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        # rows are whole sequences: columns arrive [M, T, ...] plus the
        # stored state (c0/h0 [M, hidden]); the transition PER buffer
        # handles sequence-shaped items unchanged
        self.buffer = PrioritizedReplayBuffer(
            cfg.buffer_size, cfg.prioritized_replay_alpha,
            cfg.prioritized_replay_beta, seed=cfg.seed)
        self.sampler = RecurrentInGraphSampler(
            self.env, self.module, cfg.num_envs_per_worker,
            cfg.rollout_fragment_length)
        self._carry = self.sampler.init_state(self.next_key())
        self._update_fn = jax.jit(self._sequence_update)
        self._steps_sampled = 0
        self._num_updates = 0
        self._last_target_update = 0
        self._ep_returns: list = []
        self._ep_lens: list = []

    # -- compiled sequence update -----------------------------------------

    def _sequence_update(self, params, target_params, opt_state, batch):
        """One double-Q update over [B, T, ...] sequences with burn-in.
        Returns per-sequence priorities (eta-mix of |td|)."""
        cfg = self.algo_config
        # scan wants time-major
        obs = jnp.swapaxes(batch[sb.OBS], 0, 1)          # [T, B, ...]
        actions = jnp.swapaxes(batch[sb.ACTIONS], 0, 1)
        rewards = jnp.swapaxes(batch[sb.REWARDS], 0, 1)
        dones = jnp.swapaxes(batch[sb.DONES], 0, 1).astype(jnp.float32)
        state0 = (batch["state_c"], batch["state_h"])
        bi = cfg.burn_in

        def unroll(p, s0):
            # burn-in with current params refreshes the stale stored
            # state (paper: "burn-in" beats zero-state start); no grads
            if bi > 0:
                _, s = self.module.q_unroll(
                    p, obs[:bi], dones[:bi], s0)
                s = jax.lax.stop_gradient(s)
            else:
                s = s0
            q, _ = self.module.q_unroll(p, obs[bi:], dones[bi:], s)
            return q                                      # [L, B, A]

        def loss_fn(p):
            q = unroll(p, state0)
            q_target = unroll(target_params, state0)
            a = actions[bi:].astype(jnp.int32)
            q_sel = jnp.take_along_axis(
                q[:-1], a[:-1][..., None], axis=-1)[..., 0]   # [L-1, B]
            if cfg.double_q:
                best = jnp.argmax(q[1:], axis=-1)
            else:
                best = jnp.argmax(q_target[1:], axis=-1)
            q_next = jnp.take_along_axis(
                q_target[1:], best[..., None], axis=-1)[..., 0]
            nonterm = 1.0 - dones[bi:-1]
            target = rewards[bi:-1] + cfg.gamma * nonterm * q_next
            td = q_sel - jax.lax.stop_gradient(target)
            weights = batch.get(
                "weights", jnp.ones(td.shape[1]))[None, :]
            loss = jnp.mean(weights * optax.huber_loss(
                q_sel, jax.lax.stop_gradient(target)))
            # paper's sequence priority: eta*max + (1-eta)*mean over time
            abs_td = jnp.abs(td)
            prio = (cfg.priority_eta * abs_td.max(axis=0)
                    + (1.0 - cfg.priority_eta) * abs_td.mean(axis=0))
            return loss, prio

        (loss, prio), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, prio

    # ---------------------------------------------------------------------

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0,
                   self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def compute_single_action(self, obs, state=None, explore: bool = False):
        """Stateful single-step action; returns (action, state) so
        callers thread the LSTM state (reference: Policy.compute_single_
        action state in/out)."""
        if not hasattr(self, "_act_fn"):
            self._act_fn = jax.jit(
                lambda p, o, s, k, e: self.module.compute_actions(
                    p, o, s, k, epsilon=e))
        if state is None:
            state = self.module.initial_state(1)
        eps = self._epsilon() if explore else 0.0
        a, _, new_state = self._act_fn(
            self.params, jnp.asarray(obs)[None], state, self.next_key(),
            jnp.asarray(eps))
        return int(np.asarray(a)[0]), new_state

    def training_step(self) -> dict:
        cfg = self.algo_config
        losses = []
        self._carry, traj, state0 = self.sampler.sample(
            self.params, self._carry, self.next_key(),
            jnp.asarray(self._epsilon()))
        host = {k: np.asarray(v) for k, v in traj.items()}
        rets = host.pop("episode_return").ravel()
        lens = host.pop("episode_len").ravel()
        fin = ~np.isnan(rets)
        self._ep_returns.extend(rets[fin].tolist())
        self._ep_lens.extend(lens[fin & (lens >= 0)].tolist())
        self._ep_returns = self._ep_returns[-100:]
        self._ep_lens = self._ep_lens[-100:]
        # fragments [T, num_envs, ...] -> sequence rows [num_envs, T, ...]
        rows = {k: np.swapaxes(v, 0, 1) for k, v in host.items()}
        rows["state_c"] = np.asarray(state0[0])
        rows["state_h"] = np.asarray(state0[1])
        self.buffer.add_batch(rows)
        self._steps_sampled += (cfg.rollout_fragment_length
                                * cfg.num_envs_per_worker)

        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                device_batch = {k: jnp.asarray(v)
                                for k, v in batch.items()
                                if k != "batch_indexes"}
                self.params, self.opt_state, loss, prio = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    device_batch)
                losses.append(float(loss))
                self._num_updates += 1
                self.buffer.update_priorities(
                    batch["batch_indexes"], np.asarray(prio))
                if (self._num_updates - self._last_target_update
                        >= cfg.target_network_update_freq):
                    self.target_params = jax.tree.map(
                        jnp.copy, self.params)
                    self._last_target_update = self._num_updates

        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._ep_lens))
                                 if self._ep_lens else float("nan")),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params,
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]


register_algorithm("R2D2", R2D2)
