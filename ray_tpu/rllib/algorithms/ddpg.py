"""DDPG and TD3 — deterministic-policy continuous control.

Counterpart of the reference's `rllib/algorithms/ddpg/` and `td3.py`
(ddpg_torch_policy build_ddpg_models/ddpg_actor_critic_loss): a
deterministic actor with exploration noise, Q critic(s) with polyak
targets; TD3 (`td3.py` configures DDPG with the three fixes from
Fujimoto et al.) adds twin critics with a min target, target-policy
smoothing noise, and delayed actor updates. Same TPU shape as sac.py:
compiled vmap+scan rollout, host replay, K fused updates per dispatch.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.algorithms.off_policy import (
    QNet, drain_episode_returns, scale_action, stack_replay_batches)
from ray_tpu.rllib.env.jax_env import is_jax_env, make_env
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class _DetActor(nn.Module):
    act_dim: int
    hiddens: Tuple[int, ...] = (256, 256)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return jnp.tanh(nn.Dense(self.act_dim)(x))   # [-1, 1]


class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.buffer_size = 100_000
        self.learning_starts = 1500
        self.tau = 0.005
        self.exploration_noise = 0.1      # sigma of action-space noise
        self.twin_q = False               # TD3 fix #1
        self.policy_delay = 1             # TD3 fix #2 (delayed actor)
        self.target_noise = 0.0           # TD3 fix #3 (smoothing sigma)
        self.target_noise_clip = 0.5
        self.no_done_at_end = False
        self.n_updates_per_iter = 32
        self.rollout_fragment_length = 8
        self.num_envs_per_worker = 16
        self.model = {"fcnet_hiddens": (256, 256)}


class TD3Config(DDPGConfig):
    """DDPG + the three TD3 fixes enabled (reference: td3.py defaults)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2


class DDPG(Algorithm):
    _config_class = DDPGConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_jax_env(self.env):
            raise ValueError("DDPG/TD3 require a JaxEnv (in-graph sampler)")
        if not isinstance(self.env.action_space, Box):
            raise ValueError("DDPG/TD3 require a Box action space")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._build()

    def _build(self) -> None:
        cfg = self.algo_config
        obs_dim = int(np.prod(self.env.observation_space.shape))
        self._act_dim = int(np.prod(self.env.action_space.shape))
        self._act_low = jnp.asarray(self.env.action_space.low)
        self._act_high = jnp.asarray(self.env.action_space.high)
        hiddens = tuple(cfg.model.get("fcnet_hiddens", (256, 256)))
        self.actor = _DetActor(self._act_dim, hiddens)
        self.q1 = QNet(hiddens)
        self.q2 = QNet(hiddens)
        dummy_o = jnp.zeros((1, obs_dim))
        dummy_a = jnp.zeros((1, self._act_dim))
        k1, k2, k3 = jax.random.split(self.next_key(), 3)
        self.params = {
            "actor": self.actor.init(k1, dummy_o),
            "q1": self.q1.init(k2, dummy_o, dummy_a),
            "q2": self.q2.init(k3, dummy_o, dummy_a),
        }
        self.target = jax.tree.map(jnp.copy, self.params)
        # SEPARATE optimizers for actor and critics: TD3's delayed policy
        # update must freeze the actor's params AND its Adam moments on
        # skip steps (a zero gradient through a shared Adam still moves
        # the actor via stale momentum)
        self.critic_opt = optax.adam(cfg.lr)
        self.actor_opt = optax.adam(cfg.lr)
        self.opt_state = {
            "critic": self.critic_opt.init(
                {"q1": self.params["q1"], "q2": self.params["q2"]}),
            "actor": self.actor_opt.init(self.params["actor"]),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._steps_sampled = 0
        self._updates_done = 0
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": jnp.zeros(cfg.num_envs_per_worker)}
        self._sample_fn = jax.jit(self._sample_impl)
        self._update_many_fn = jax.jit(self._update_many)
        self._ep_returns: list = []

    def _scale(self, act_tanh):
        return scale_action(self._act_low, self._act_high, act_tanh)

    # -- compiled rollout --------------------------------------------------

    def _sample_impl(self, params, carry, key):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_noise, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            act = self.actor.apply(params["actor"], obs)
            noise = cfg.exploration_noise * jax.random.normal(
                k_noise, act.shape)
            act = jnp.clip(act + noise, -1.0, 1.0)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], self._scale(act), env_keys)
            ep_ret = carry["ep_ret"] + reward
            out = {sb.OBS: obs, sb.ACTIONS: act, sb.REWARDS: reward,
                   sb.NEXT_OBS: next_obs, sb.DONES: done,
                   "episode_return": jnp.where(done, ep_ret, jnp.nan)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        return jax.lax.scan(one_step, carry, keys)

    # -- fused update ------------------------------------------------------

    def _one_update(self, params, target, opt_state, batch, key,
                    update_idx):
        cfg = self.algo_config

        def critic_loss_fn(cp):
            act_t = self.actor.apply(target["actor"], batch[sb.NEXT_OBS])
            if cfg.target_noise > 0:
                noise = jnp.clip(
                    cfg.target_noise * jax.random.normal(key, act_t.shape),
                    -cfg.target_noise_clip, cfg.target_noise_clip)
                act_t = jnp.clip(act_t + noise, -1.0, 1.0)
            tq1 = self.q1.apply(target["q1"], batch[sb.NEXT_OBS], act_t)
            if cfg.twin_q:
                tq2 = self.q2.apply(target["q2"], batch[sb.NEXT_OBS], act_t)
                tq = jnp.minimum(tq1, tq2)
            else:
                tq = tq1
            if cfg.no_done_at_end:
                nonterm = jnp.ones_like(batch[sb.REWARDS])
            else:
                nonterm = 1.0 - batch[sb.DONES].astype(jnp.float32)
            y = jax.lax.stop_gradient(
                batch[sb.REWARDS] + cfg.gamma * nonterm * tq)
            q1 = self.q1.apply(cp["q1"], batch[sb.OBS], batch[sb.ACTIONS])
            loss = jnp.mean((q1 - y) ** 2)
            if cfg.twin_q:
                q2 = self.q2.apply(cp["q2"], batch[sb.OBS],
                                   batch[sb.ACTIONS])
                loss = loss + jnp.mean((q2 - y) ** 2)
            return loss

        cparams = {"q1": params["q1"], "q2": params["q2"]}
        critic_loss, cgrads = jax.value_and_grad(critic_loss_fn)(cparams)
        cupd, copt = self.critic_opt.update(
            cgrads, opt_state["critic"], cparams)
        cparams = optax.apply_updates(cparams, cupd)

        def actor_loss_fn(ap):
            act = self.actor.apply(ap, batch[sb.OBS])
            q = self.q1.apply(jax.lax.stop_gradient(cparams["q1"]),
                              batch[sb.OBS], act)
            return -jnp.mean(q)

        def do_actor(ap_opt):
            ap, aopt = ap_opt
            _, agrads = jax.value_and_grad(actor_loss_fn)(ap)
            aupd, aopt = self.actor_opt.update(agrads, aopt, ap)
            return optax.apply_updates(ap, aupd), aopt

        # TD3 fix #2: on skip steps BOTH the actor params and its
        # optimizer state pass through untouched
        aparams, aopt = jax.lax.cond(
            update_idx % cfg.policy_delay == 0,
            do_actor, lambda x: x,
            (params["actor"], opt_state["actor"]))

        params = {"actor": aparams, "q1": cparams["q1"],
                  "q2": cparams["q2"]}
        opt_state = {"critic": copt, "actor": aopt}
        target = jax.tree.map(
            lambda t, o: (1 - cfg.tau) * t + cfg.tau * o, target, params)
        return params, target, opt_state, critic_loss

    def _update_many(self, params, target, opt_state, batches, key,
                     start_idx):
        keys = jax.random.split(key, batches[sb.REWARDS].shape[0])
        idxs = start_idx + jnp.arange(batches[sb.REWARDS].shape[0])

        def one(state, xs):
            params, target, opt_state = state
            batch, k, i = xs
            params, target, opt_state, loss = self._one_update(
                params, target, opt_state, batch, k, i)
            return (params, target, opt_state), loss

        (params, target, opt_state), losses = jax.lax.scan(
            one, (params, target, opt_state), (batches, keys, idxs))
        return params, target, opt_state, losses

    # ----------------------------------------------------------------------

    def training_step(self) -> dict:
        cfg = self.algo_config
        self._carry, traj = self._sample_fn(
            self.params, self._carry, self.next_key())
        host = {k: np.asarray(v) for k, v in traj.items()}
        flat = drain_episode_returns(host, self._ep_returns)
        self.buffer.add_batch(flat)
        self._steps_sampled += len(flat[sb.REWARDS])

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            batches = stack_replay_batches(
                self.buffer, cfg.n_updates_per_iter, cfg.train_batch_size)
            (self.params, self.target, self.opt_state,
             loss_v) = self._update_many_fn(
                self.params, self.target, self.opt_state, batches,
                self.next_key(), jnp.asarray(self._updates_done))
            self._updates_done += cfg.n_updates_per_iter
            losses = np.asarray(loss_v).tolist()
        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def compute_single_action(self, obs, explore: bool = False):
        obs = jnp.asarray(obs)[None]
        act = self.actor.apply(self.params["actor"], obs)
        if explore:
            act = jnp.clip(
                act + self.algo_config.exploration_noise
                * jax.random.normal(self.next_key(), act.shape),
                -1.0, 1.0)
        return np.asarray(self._scale(act))[0]

    def get_state(self) -> dict:
        return {"params": self.params, "target": self.target,
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target = state["target"]
        self.opt_state = state["opt_state"]


class TD3(DDPG):
    _config_class = TD3Config


register_algorithm("DDPG", DDPG)
register_algorithm("TD3", TD3)
