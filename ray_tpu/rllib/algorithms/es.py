"""ES — evolution strategies (Salimans et al., 2017).

Counterpart of the reference's `rllib/algorithms/es/` (es.py: a head
broadcasts a params seed, CPU workers evaluate antithetic perturbations,
returns are centered-rank-transformed into a gradient estimate). The
TPU-native rewrite is WHOLE-POPULATION-IN-GRAPH: the population of
perturbed policies and all their rollouts run as one vmapped, scanned,
jitted program — no actor fleet, no parameter shipping; the machine that
made ES famous for wall-clock (thousands of CPU cores) is replaced by
one compiled program that vectorizes population x envs x time on the
accelerator.
"""

from __future__ import annotations

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import is_jax_env


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.lr = 0.02
        self.population_size = 64       # antithetic pairs: 2x evaluations
        self.noise_stdev = 0.05
        self.episode_horizon = 200      # fitness = return over horizon
        self.l2_coeff = 0.005
        self.model = {"fcnet_hiddens": (32, 32)}


def _centered_ranks(x):
    """Fitness shaping: returns -> ranks -> [-0.5, 0.5] (ES paper §2)."""
    ranks = jnp.argsort(jnp.argsort(x))
    return ranks.astype(jnp.float32) / (x.shape[0] - 1) - 0.5


class ES(Algorithm):
    _config_class = ESConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        if not is_jax_env(self.env):
            raise ValueError("ES requires a JaxEnv (in-graph rollouts)")

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.optimizer = optax.adam(cfg.lr)
        self._flat, self._unravel = jax.flatten_util.ravel_pytree(
            self.params)
        self.opt_state = self.optimizer.init(self._flat)
        self._step_fn = jax.jit(self._es_step)
        self._iter = 0

    # -- fitness of ONE parameter vector (greedy policy, fixed horizon) --

    def _episode_return(self, flat_params, key):
        params = self._unravel(flat_params)
        k_reset, k_run = jax.random.split(key)
        state, obs = self.env.reset(k_reset)

        def step(carry, k):
            state, obs, ret, alive = carry
            actions, _, _ = self.module.compute_actions(
                params, obs[None], k, explore=False)
            state, obs, r, done, _ = self.env.step(
                state, jnp.squeeze(actions, 0), k)
            # fitness is the FIRST episode's return: rewards after the
            # first termination are masked (the env auto-resets, and on
            # +1/step tasks an unmasked fixed-horizon sum would score
            # every policy identically)
            ret = ret + r * alive
            alive = alive * (1.0 - done.astype(jnp.float32))
            return (state, obs, ret, alive), None

        keys = jax.random.split(k_run, self.algo_config.episode_horizon)
        (_, _, ret, _), _ = jax.lax.scan(
            step, (state, obs, 0.0, 1.0), keys)
        return ret

    def _es_step(self, flat, opt_state, key):
        cfg = self.algo_config
        n = cfg.population_size
        k_noise, k_eval = jax.random.split(key)
        eps = jax.random.normal(k_noise, (n, flat.shape[0]),
                                dtype=flat.dtype)
        # antithetic pairs share an eval key so the ONLY difference
        # between +eps and -eps fitness is the perturbation (common
        # random numbers, the paper's variance-reduction trick)
        eval_keys = jax.random.split(k_eval, n)
        cand_plus = flat[None, :] + cfg.noise_stdev * eps
        cand_minus = flat[None, :] - cfg.noise_stdev * eps
        r_plus = jax.vmap(self._episode_return)(cand_plus, eval_keys)
        r_minus = jax.vmap(self._episode_return)(cand_minus, eval_keys)
        ranked = _centered_ranks(jnp.concatenate([r_plus, r_minus]))
        w = ranked[:n] - ranked[n:]
        grad = -(w @ eps) / (n * cfg.noise_stdev) + cfg.l2_coeff * flat
        updates, opt_state = self.optimizer.update(grad, opt_state, flat)
        flat = optax.apply_updates(flat, updates)
        return flat, opt_state, {
            "episode_reward_mean": jnp.mean(
                jnp.concatenate([r_plus, r_minus])),
            "episode_reward_max": jnp.maximum(jnp.max(r_plus),
                                              jnp.max(r_minus)),
        }

    def training_step(self) -> dict:
        self._flat, self.opt_state, stats = self._step_fn(
            self._flat, self.opt_state, self.next_key())
        self._iter += 1
        self.params = self._unravel(self._flat)
        return {
            "episode_reward_mean": float(stats["episode_reward_mean"]),
            "episode_reward_max": float(stats["episode_reward_max"]),
            "episodes_this_iter": 2 * self.algo_config.population_size,
            "training_iteration": self._iter,
        }

    def get_state(self) -> dict:
        return {"params": self.params,
                "flat": np.asarray(self._flat),
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self._flat = jnp.asarray(state["flat"])
        self.opt_state = state["opt_state"]


register_algorithm("ES", ES)
