"""PG — vanilla policy gradient / REINFORCE (Williams 1992).

Counterpart of the reference's `rllib/algorithms/pg/pg.py` (the simplest
on-policy baseline in its roster: Monte-Carlo returns, no critic, no
clipping). TPU-first shape matches PPO's in-graph path: rollout
(vmap+scan), reward-to-go (reverse scan) and the gradient step compile
as ONE jitted program per iteration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import is_jax_env
from ray_tpu.rllib.rollout import InGraphSampler, episode_stats


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self.lr = 4e-3
        self.gamma = 0.99
        self.rollout_fragment_length = 128
        self.num_envs_per_worker = 16
        # reward-to-go is standardized per batch (the standard variance
        # reduction; the reference's PG leaves returns raw)
        self.standardize_returns = True


def _rewards_to_go(rewards, dones, gamma):
    """[T, B] discounted reward-to-go, zeroed across episode bounds."""

    def back(acc, xs):
        r, d = xs
        acc = r + gamma * acc * (1.0 - d.astype(jnp.float32))
        return acc, acc

    _, rtg = jax.lax.scan(back, jnp.zeros(rewards.shape[1:]),
                          (rewards, dones), reverse=True)
    return rtg


class PG(Algorithm):
    _config_class = PGConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        if not is_jax_env(self.env):
            raise ValueError("PG v1 requires a JaxEnv (in-graph rollouts)")

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.sampler = InGraphSampler(
            self.env, self.module, cfg.num_envs_per_worker,
            cfg.rollout_fragment_length)
        self._carry = self.sampler.init_state(self.next_key())
        # NB: named _pg_iteration, not _iteration — Trainable.__init__
        # stores the training-iteration COUNTER as self._iteration, which
        # shadows a method of the same name (jax.jit(0) -> TypeError).
        self._train_fn = jax.jit(self._pg_iteration)

    def _pg_iteration(self, params, opt_state, carry, key):
        cfg = self.algo_config
        carry, traj, _ = self.sampler._unroll_impl(params, carry, key)
        rtg = _rewards_to_go(traj[sb.REWARDS], traj[sb.DONES], cfg.gamma)
        if cfg.standardize_returns:
            rtg = (rtg - rtg.mean()) / (rtg.std() + 1e-8)

        def loss_fn(p):
            dist, _ = self.module.forward(p, traj[sb.OBS])
            logp = dist.logp(traj[sb.ACTIONS])
            pg_loss = -jnp.mean(logp * rtg)
            return pg_loss, {"policy_loss": pg_loss,
                             "entropy": jnp.mean(dist.entropy())}

        (_, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        ep = {"episode_return": traj["episode_return"],
              "episode_len": traj["episode_len"]}
        return params, opt_state, carry, stats, ep

    def training_step(self) -> dict:
        self.params, self.opt_state, self._carry, stats, ep = \
            self._train_fn(self.params, self.opt_state, self._carry,
                           self.next_key())
        metrics = episode_stats(ep)
        metrics.update({k: float(np.asarray(v)) for k, v in stats.items()})
        return metrics

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("PG", PG)
