"""Algorithm base class + typed config builder.

Counterpart of the reference's `rllib/algorithms/algorithm.py:191`
(`Algorithm(Trainable)`: step :813, training_step :1400) and
`algorithm_config.py` (`AlgorithmConfig` fluent builder). An Algorithm IS
a `ray_tpu.tune.Trainable`, so `tune.run(PPO, ...)` and
`Tuner(PPO, ...)` work like the reference's Tune integration.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Type

import jax
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.jax_env import is_jax_env, make_env
from ray_tpu.tune.trainable import Trainable

_ALGORITHMS: Dict[str, Type["Algorithm"]] = {}


def register_algorithm(name: str, cls: Type["Algorithm"]) -> None:
    _ALGORITHMS[name] = cls


def get_algorithm_class(name: str) -> Type["Algorithm"]:
    """Reference: `rllib/algorithms/registry.py`."""
    try:
        return _ALGORITHMS[name]
    except KeyError:
        raise KeyError(f"unknown algorithm {name!r} "
                       f"(known: {sorted(_ALGORITHMS)})") from None


class AlgorithmConfig:
    """Fluent builder; `.build()` makes the Algorithm, `.to_dict()` feeds
    Tune param spaces."""

    # subclass override
    algo_class: Optional[Type["Algorithm"]] = None

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        if algo_class is not None:
            self.algo_class = algo_class
        # environment
        self.env = None
        self.env_config: dict = {}
        # rollouts
        self.num_rollout_workers = 0
        self.num_envs_per_worker = 8
        self.rollout_fragment_length = 128
        self.seed = 0
        # connector pipelines between env and module on the eager
        # rollout paths (ray_tpu.rllib.connectors; reference:
        # rllib/connectors/ agent+action pipelines)
        self.observation_connectors = None
        self.action_connectors = None
        # training
        self.lr = 5e-4
        self.gamma = 0.99
        self.train_batch_size = 1024
        self.model: dict = {}
        self.optimizer_name = "adam"
        self.grad_clip: Optional[float] = None
        # resources
        self.num_cpus_per_worker = 1
        self.num_tpus_per_learner = 0

    # -- fluent sections (each returns self, like the reference) ---------

    def environment(self, env=None, *, env_config: dict | None = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def rollouts(self, *, num_rollout_workers: int | None = None,
                 num_envs_per_worker: int | None = None,
                 rollout_fragment_length: int | None = None,
                 observation_connectors=None, action_connectors=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if observation_connectors is not None:
            self.observation_connectors = observation_connectors
        if action_connectors is not None:
            self.action_connectors = action_connectors
        return self

    def connector_dict(self) -> dict | None:
        if self.observation_connectors is None \
                and self.action_connectors is None:
            return None
        return {"obs": self.observation_connectors,
                "action": self.action_connectors}

    env_runners = rollouts      # new-stack alias in the reference

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def resources(self, *, num_cpus_per_worker: int | None = None,
                  num_tpus_per_learner: int | None = None):
        if num_cpus_per_worker is not None:
            self.num_cpus_per_worker = num_cpus_per_worker
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, *, seed: int | None = None):
        if seed is not None:
            self.seed = seed
        return self

    def framework(self, *_args, **_kw):     # API-compat no-op (JAX only)
        return self

    # ---------------------------------------------------------------------

    def to_dict(self) -> dict:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    def update_from_dict(self, d: dict) -> "AlgorithmConfig":
        for k, v in d.items():
            setattr(self, k, v)
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("config has no algo_class bound")
        return self.algo_class(config=self)


class Algorithm(Trainable):
    """One RL algorithm instance: env + module + learner state.

    As a tune.Trainable: step() == one training iteration; checkpoints
    carry params/opt-state; `tune.run(PPO, config={...})` sweeps the
    config dict (merged into the default AlgorithmConfig).
    """

    _config_class: Type[AlgorithmConfig] = AlgorithmConfig

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._config_class()

    def __init__(self, config=None, trial_dir: str | None = None, env=None):
        if isinstance(config, AlgorithmConfig):
            cfg = config.copy()
        else:
            cfg = self.get_default_config()
            cfg.update_from_dict(dict(config or {}))
        if env is not None:
            cfg.env = env
        self.algo_config = cfg
        super().__init__(cfg.to_dict(), trial_dir)

    # -- Trainable plumbing ----------------------------------------------

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.env = make_env(cfg.env, cfg.env_config)
        self.module = RLModule(self.env.observation_space,
                               self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.module.init(init_key)
        self._np_rng = np.random.default_rng(cfg.seed)
        self.build_learner()

    def next_key(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    def build_learner(self) -> None:
        """Subclass hook: create optimizer/sampler state."""
        raise NotImplementedError

    def training_step(self) -> dict:
        """Subclass hook: one iteration (sample + update), returns
        metrics (reference: algorithm.py:1400)."""
        raise NotImplementedError

    def step(self) -> dict:
        result = self.training_step()
        return result

    # convenience mirroring the reference's train() use outside Tune
    def get_policy_params(self):
        return self.params

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp
        obs = jnp.asarray(obs)[None]
        actions, _, _ = self.module.compute_actions(
            self.params, obs, self.next_key(), explore=explore)
        a = np.asarray(actions)[0]
        return a.item() if a.ndim == 0 else a

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str):
        state = jax.tree.map(np.asarray, self.get_state())
        return {"state": state}

    def load_checkpoint(self, data) -> None:
        if isinstance(data, dict) and "state" in data:
            self.set_state(data["state"])

    def get_state(self) -> dict:
        return {"params": self.params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]

    def cleanup(self) -> None:
        workers = getattr(self, "workers", None)
        if workers is not None:
            workers.stop()


def _concat_env_check(env) -> bool:
    return is_jax_env(env)
