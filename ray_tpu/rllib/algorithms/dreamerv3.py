"""DreamerV3 — world-model RL: learn the environment, act in
imagination (Hafner et al. 2023).

Counterpart of the reference's `rllib/algorithms/dreamerv3/` (tf-based
RSSM world model + imagination actor-critic). Compact v1 for vector
observations, keeping the parts that make Dreamer Dreamer:

- RSSM: deterministic GRU path + CATEGORICAL stochastic latents with
  straight-through gradients; posterior from (h, obs), prior from h.
- World-model loss: reconstruction + reward + continue heads, KL with
  free bits and dyn/rep balancing (the V3 stabilizers).
- Behavior: actor-critic trained entirely on IMAGINED rollouts from
  replayed posterior states — lambda-returns, EMA target critic,
  REINFORCE actor with entropy (the discrete-action V3 recipe).

TPU-first shape: collection is one compiled scan carrying (h, z)
through the rollout (same stored-state pattern as core/recurrent.py),
the world-model update is one jitted program over [B, L] sequences, and
imagination is a jitted scan — three compiled programs per iteration,
no eager stepping anywhere.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import is_jax_env
from ray_tpu.rllib.env.spaces import Discrete
from ray_tpu.rllib.replay_buffers import ReplayBuffer


def _onehot_st(logits, key):
    """Sample categorical one-hot with straight-through gradients."""
    idx = jax.random.categorical(key, logits, axis=-1)
    hard = jax.nn.one_hot(idx, logits.shape[-1])
    soft = jax.nn.softmax(logits)
    return soft + jax.lax.stop_gradient(hard - soft)


class _RSSM(nn.Module):
    deter: int
    groups: int          # number of categorical groups
    classes: int         # classes per group
    hidden: int

    def setup(self):
        self.gru = nn.GRUCell(features=self.deter)
        self.inp = nn.Dense(self.hidden)
        self.prior_net = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu,
             nn.Dense(self.groups * self.classes)])
        self.post_net = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu,
             nn.Dense(self.groups * self.classes)])

    def _stoch_dim(self):
        return self.groups * self.classes

    def step(self, h, z_flat, action_onehot, embed, key):
        """One posterior step: (h, z, a) -> h'; posterior(h', obs)."""
        x = nn.silu(self.inp(jnp.concatenate([z_flat, action_onehot],
                                             -1)))
        h, _ = self.gru(h, x)
        prior_logits = self.prior_net(h).reshape(
            *h.shape[:-1], self.groups, self.classes)
        post_logits = self.post_net(
            jnp.concatenate([h, embed], -1)).reshape(
            *h.shape[:-1], self.groups, self.classes)
        z = _onehot_st(post_logits, key).reshape(
            *h.shape[:-1], self._stoch_dim())
        return h, z, prior_logits, post_logits

    def imagine_step(self, h, z_flat, action_onehot, key):
        x = nn.silu(self.inp(jnp.concatenate([z_flat, action_onehot],
                                             -1)))
        h, _ = self.gru(h, x)
        prior_logits = self.prior_net(h).reshape(
            *h.shape[:-1], self.groups, self.classes)
        z = _onehot_st(prior_logits, key).reshape(
            *h.shape[:-1], self._stoch_dim())
        return h, z


class _WorldModel(nn.Module):
    obs_dim: int
    num_actions: int
    deter: int = 128
    groups: int = 8
    classes: int = 8
    hidden: int = 128

    def setup(self):
        self.rssm = _RSSM(self.deter, self.groups, self.classes,
                          self.hidden)
        self.encoder = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu, nn.Dense(self.hidden)])
        self.decoder = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu, nn.Dense(self.obs_dim)])
        self.reward_head = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu, nn.Dense(1)])
        self.cont_head = nn.Sequential(
            [nn.Dense(self.hidden), nn.silu, nn.Dense(1)])

    def initial(self, batch):
        return (jnp.zeros((batch, self.deter)),
                jnp.zeros((batch, self.groups * self.classes)))

    def encode(self, obs):
        return self.encoder(obs)

    def post_step(self, h, z, a_onehot, embed, key):
        return self.rssm.step(h, z, a_onehot, embed, key)

    def prior_step(self, h, z, a_onehot, key):
        return self.rssm.imagine_step(h, z, a_onehot, key)

    def decode(self, h, z):
        feat = jnp.concatenate([h, z], -1)
        return (self.decoder(feat), self.reward_head(feat)[..., 0],
            self.cont_head(feat)[..., 0])

    def init_all(self, obs, a_onehot, key):
        """Touch every submodule once so init() creates all params."""
        h, z = self.initial(obs.shape[0])
        embed = self.encoder(obs)
        h, z, _, _ = self.rssm.step(h, z, a_onehot, embed, key)
        self.rssm.imagine_step(h, z, a_onehot, key)
        return self.decode(h, z)



class _MLPHead(nn.Module):
    out: int
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        h = nn.silu(nn.Dense(self.hidden)(x))
        h = nn.silu(nn.Dense(self.hidden)(h))
        return nn.Dense(self.out)(h)


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DreamerV3)
        self.model_lr = 6e-4
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.batch_size = 16              # sequences per update
        # sequence length per training row == rollout_fragment_length
        self.horizon = 15
        self.gamma = 0.985
        self.lambda_ = 0.95
        self.free_bits = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.entropy_coeff = 3e-3
        self.critic_ema = 0.98
        self.n_updates_per_iter = 8
        self.num_envs_per_worker = 16
        self.rollout_fragment_length = 32
        self.buffer_size = 2000           # sequences
        self.learning_starts = 64         # sequences
        self.deter = 128
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.hidden = 128


class DreamerV3(Algorithm):
    _config_class = DreamerV3Config

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_jax_env(self.env):
            raise ValueError("DreamerV3 v1 requires a JaxEnv")
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("DreamerV3 v1 supports Discrete actions")
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.num_actions = self.env.action_space.n
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.wm = _WorldModel(self.obs_dim, self.num_actions, cfg.deter,
                              cfg.stoch_groups, cfg.stoch_classes,
                              cfg.hidden)
        feat_dim = cfg.deter + cfg.stoch_groups * cfg.stoch_classes
        self.actor = _MLPHead(self.num_actions, cfg.hidden)
        self.critic = _MLPHead(1, cfg.hidden)

        k1, k2, k3 = jax.random.split(self.next_key(), 3)
        B = 2
        self.wm_params = self.wm.init(
            {"params": k1}, jnp.zeros((B, self.obs_dim)),
            jnp.zeros((B, self.num_actions)), k1,
            method=_WorldModel.init_all)
        self.actor_params = self.actor.init(k2, jnp.zeros((1, feat_dim)))
        self.critic_params = self.critic.init(k3,
                                              jnp.zeros((1, feat_dim)))
        self.target_critic = jax.tree.map(jnp.copy, self.critic_params)

        self.wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(cfg.model_lr))
        self.actor_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                     optax.adam(cfg.actor_lr))
        self.critic_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                      optax.adam(cfg.critic_lr))
        self.wm_opt_state = self.wm_opt.init(self.wm_params)
        self.actor_opt_state = self.actor_opt.init(self.actor_params)
        self.critic_opt_state = self.critic_opt.init(self.critic_params)

        # sequence replay (columns are [T, ...] rows like R2D2)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._sample_fn = jax.jit(self._collect)
        self._wm_update_fn = jax.jit(self._wm_update)
        self._behavior_fn = jax.jit(self._behavior_update)
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {
            "env_state": state, "obs": obs,
            "wm_state": self.wm.initial(cfg.num_envs_per_worker),
            "prev_action": jnp.zeros(
                (cfg.num_envs_per_worker, self.num_actions)),
            "is_first": jnp.ones(cfg.num_envs_per_worker),
            "ep_ret": jnp.zeros(cfg.num_envs_per_worker),
            "ep_len": jnp.zeros(cfg.num_envs_per_worker, jnp.int32),
        }
        self._steps_sampled = 0
        self._ep_returns: list = []
        self._ep_lens: list = []

    # -- compiled collection (posterior-state policy) ----------------------

    def _policy_feat(self, actor_params, feat, key):
        logits = self.actor.apply(actor_params, feat)
        a = jax.random.categorical(key, logits)
        return a, logits

    def _collect(self, wm_params, actor_params, carry, key):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_a, k_z, k_env = jax.random.split(step_key, 3)
            obs = carry["obs"].reshape(cfg.num_envs_per_worker, -1)
            h, z = carry["wm_state"]
            mask = (1.0 - carry["is_first"])[:, None]
            h, z = h * mask, z * mask
            prev_a = carry["prev_action"] * mask
            embed = self.wm.apply(wm_params, obs,
                                  method=_WorldModel.encode)
            h, z, _, _ = self.wm.apply(
                wm_params, h, z, prev_a, embed, k_z,
                method=_WorldModel.post_step)
            feat = jnp.concatenate([h, z], -1)
            a, _ = self._policy_feat(actor_params, feat, k_a)
            a_onehot = jax.nn.one_hot(a, self.num_actions)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], a, env_keys)
            ep_ret = carry["ep_ret"] + reward
            ep_len = carry["ep_len"] + 1
            out = {"obs": obs, "action": a_onehot, "reward": reward,
                   "done": done.astype(jnp.float32),
                   "is_first": carry["is_first"],
                   "episode_return": jnp.where(done, ep_ret, jnp.nan),
                   "episode_len": jnp.where(done, ep_len, -1)}
            new_carry = {
                "env_state": state, "obs": next_obs,
                "wm_state": (h, z), "prev_action": a_onehot,
                "is_first": done.astype(jnp.float32),
                "ep_ret": jnp.where(done, 0.0, ep_ret),
                "ep_len": jnp.where(done, 0, ep_len),
            }
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        carry, traj = jax.lax.scan(one_step, carry, keys)
        return carry, traj

    # -- world model update ------------------------------------------------

    def _wm_update(self, wm_params, opt_state, obs, act, reward, done,
                  is_first, key):
        cfg = self.algo_config

        def loss_fn(p):
            B = obs.shape[1]
            L = obs.shape[0]
            embeds = self.wm.apply(p, obs, method=_WorldModel.encode)
            # the transition into obs[t] is conditioned on the PREVIOUS
            # action (same convention as collection) — conditioning on
            # act[t] would let the model peek at the action chosen
            # AFTER seeing obs[t]
            prev_act = jnp.concatenate(
                [jnp.zeros_like(act[:1]), act[:-1]], 0)

            def step(carry, xs):
                h, z = carry
                embed, a_onehot, first, k = xs
                mask = (1.0 - first)[:, None]
                h, z = h * mask, z * mask
                a_onehot = a_onehot * mask
                h, z, prior_l, post_l = self.wm.apply(
                    p, h, z, a_onehot, embed, k,
                    method=_WorldModel.post_step)
                return (h, z), (h, z, prior_l, post_l)

            keys = jax.random.split(key, L)
            state = (jnp.zeros((B, cfg.deter)),
                     jnp.zeros((B, cfg.stoch_groups * cfg.stoch_classes)))
            (_, _), (hs, zs, prior_l, post_l) = jax.lax.scan(
                step, state, (embeds, prev_act, is_first, keys))
            recon, rew_pred, cont_pred = self.wm.apply(
                p, hs, zs, method=_WorldModel.decode)
            recon_loss = jnp.mean(jnp.sum((recon - obs) ** 2, -1))
            reward_loss = jnp.mean((rew_pred - reward) ** 2)
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(cont_pred, 1.0 - done))
            # KL with free bits + dyn/rep balancing (V3 stabilizers)
            post = jax.nn.log_softmax(post_l)
            prior = jax.nn.log_softmax(prior_l)
            p_post = jnp.exp(post)
            kl_dyn = jnp.sum(jax.lax.stop_gradient(p_post)
                             * (jax.lax.stop_gradient(post) - prior),
                             (-1,)).sum(-1)
            kl_rep = jnp.sum(p_post
                             * (post - jax.lax.stop_gradient(prior)),
                             (-1,)).sum(-1)
            kl = (cfg.kl_dyn_scale
                  * jnp.maximum(kl_dyn, cfg.free_bits).mean()
                  + cfg.kl_rep_scale
                  * jnp.maximum(kl_rep, cfg.free_bits).mean())
            loss = recon_loss + reward_loss + cont_loss + kl
            return loss, (hs, zs, recon_loss, kl)

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(wm_params)
        updates, opt_state = self.wm_opt.update(grads, opt_state,
                                                wm_params)
        return (optax.apply_updates(wm_params, updates), opt_state,
                loss, aux)

    # -- behavior (imagination) update -------------------------------------

    def _behavior_update(self, wm_params, actor_params, critic_params,
                         target_critic, a_opt, c_opt, hs, zs, key):
        cfg = self.algo_config
        # flatten replayed posterior states into imagination start points
        h0 = hs.reshape(-1, hs.shape[-1])
        z0 = zs.reshape(-1, zs.shape[-1])

        feat0 = jnp.concatenate([h0, z0], -1)

        # --- ONE imagination rollout (actor sampled, no grads here) ---
        def step(carry, k):
            h, z = carry
            k_a, k_z = jax.random.split(k)
            feat = jnp.concatenate([h, z], -1)
            logits = self.actor.apply(actor_params, feat)
            a = jax.random.categorical(k_a, logits)
            a_onehot = jax.nn.one_hot(a, self.num_actions)
            h2, z2 = self.wm.apply(wm_params, h, z, a_onehot, k_z,
                                   method=_WorldModel.prior_step)
            return (h2, z2), (feat, a_onehot, h2, z2)

        keys = jax.random.split(key, cfg.horizon)
        _, (featPre, actsI, hsI, zsI) = jax.lax.scan(
            step, (h0, z0), keys)
        featPre = jax.lax.stop_gradient(featPre)   # s_0..s_{H-1} [H,N,F]
        actsI = jax.lax.stop_gradient(actsI)
        featPost = jnp.concatenate([hsI, zsI], -1)  # s_1..s_H
        _, rew, cont = self.wm.apply(wm_params, hsI, zsI,
                                     method=_WorldModel.decode)
        disc = jax.nn.sigmoid(cont) * cfg.gamma     # at s_1..s_H

        featAll = jnp.concatenate([feat0[None], featPost],
                                  0)                # s_0..s_H [H+1,N,F]
        v_t = self.critic.apply(target_critic, featAll)[..., 0]

        # lambda-returns for s_0..s_{H-1}: R[t] = r_{t+1} + d_{t+1} *
        # ((1-lam) V(s_{t+1}) + lam R[t+1]); bootstrap V(s_H)
        def lam_scan(carry, xs):
            r, d, v_next = xs
            ret = r + d * ((1 - cfg.lambda_) * v_next + cfg.lambda_ * carry)
            return ret, ret

        _, returns = jax.lax.scan(
            lam_scan, v_t[-1], (rew[::-1], disc[::-1], v_t[1:][::-1]))
        returns = returns[::-1]                      # [H, N] for s_0..s_{H-1}
        returns = jax.lax.stop_gradient(returns)
        # weight[t] = prod_{i<=t-1} disc(s_{i+1}); weight[0] = 1
        weights = jnp.cumprod(
            jnp.concatenate([jnp.ones_like(disc[:1]), disc[:-1]], 0),
            0)[:cfg.horizon]
        weights = jax.lax.stop_gradient(weights)
        # ACTION-INDEPENDENT baseline: V(s_t), the state acted FROM
        baseline = v_t[:-1]
        adv = jax.lax.stop_gradient(returns - baseline)
        scale = jax.lax.stop_gradient(
            jnp.maximum(1.0, jnp.percentile(jnp.abs(adv), 95)))

        # actor: re-apply the MLP on the FROZEN features with the stored
        # sampled actions — differentiable logp/entropy without
        # re-running the RSSM rollout
        def actor_loss_fn(p_actor):
            logits = self.actor.apply(p_actor, featPre)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.sum(logp_all * actsI, -1)
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
            return -(weights * (logp * adv / scale
                                + cfg.entropy_coeff * entropy)).mean()

        a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(actor_params)
        a_updates, a_opt = self.actor_opt.update(a_grads, a_opt,
                                                 actor_params)
        actor_params = optax.apply_updates(actor_params, a_updates)

        def critic_loss_fn(p_critic):
            v = self.critic.apply(p_critic, featPre)[..., 0]
            return (weights * (v - returns) ** 2).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            critic_params)
        c_updates, c_opt = self.critic_opt.update(c_grads, c_opt,
                                                  critic_params)
        critic_params = optax.apply_updates(critic_params, c_updates)
        target_critic = jax.tree.map(
            lambda t, o: cfg.critic_ema * t + (1 - cfg.critic_ema) * o,
            target_critic, critic_params)
        return (actor_params, critic_params, target_critic, a_opt,
                c_opt, a_loss, c_loss)

    # ---------------------------------------------------------------------

    def training_step(self) -> dict:
        cfg = self.algo_config
        self._carry, traj = self._sample_fn(
            self.wm_params, self.actor_params, self._carry,
            self.next_key())
        host = {k: np.asarray(v) for k, v in traj.items()}
        rets = host.pop("episode_return").ravel()
        lens = host.pop("episode_len").ravel()
        fin = ~np.isnan(rets)
        self._ep_returns.extend(rets[fin].tolist())
        self._ep_lens.extend(lens[fin & (lens >= 0)].tolist())
        self._ep_returns = self._ep_returns[-100:]
        self._ep_lens = self._ep_lens[-100:]
        rows = {k: np.swapaxes(v, 0, 1) for k, v in host.items()}
        self.buffer.add_batch(rows)
        self._steps_sampled += (cfg.rollout_fragment_length
                                * cfg.num_envs_per_worker)

        wm_losses, a_losses, c_losses, recons = [], [], [], []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = self.buffer.sample(cfg.batch_size)
                obs = jnp.asarray(np.swapaxes(batch["obs"], 0, 1))
                act = jnp.asarray(np.swapaxes(batch["action"], 0, 1))
                rew = jnp.asarray(np.swapaxes(batch["reward"], 0, 1))
                done = jnp.asarray(np.swapaxes(batch["done"], 0, 1))
                first = jnp.asarray(np.swapaxes(batch["is_first"], 0, 1))
                (self.wm_params, self.wm_opt_state, wloss,
                 (hs, zs, recon, kl)) = self._wm_update_fn(
                    self.wm_params, self.wm_opt_state, obs, act, rew,
                    done, first, self.next_key())
                (self.actor_params, self.critic_params,
                 self.target_critic, self.actor_opt_state,
                 self.critic_opt_state, a_loss, c_loss) = \
                    self._behavior_fn(
                        self.wm_params, self.actor_params,
                        self.critic_params, self.target_critic,
                        self.actor_opt_state, self.critic_opt_state,
                        jax.lax.stop_gradient(hs),
                        jax.lax.stop_gradient(zs), self.next_key())
                wm_losses.append(float(wloss))
                a_losses.append(float(a_loss))
                c_losses.append(float(c_loss))
                recons.append(float(recon))

        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._ep_lens))
                                 if self._ep_lens else float("nan")),
            "world_model_loss": (float(np.mean(wm_losses))
                                 if wm_losses else float("nan")),
            "recon_loss": (float(np.mean(recons))
                           if recons else float("nan")),
            "actor_loss": (float(np.mean(a_losses))
                           if a_losses else float("nan")),
            "critic_loss": (float(np.mean(c_losses))
                            if c_losses else float("nan")),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def get_state(self) -> dict:
        return {"wm_params": self.wm_params,
                "actor_params": self.actor_params,
                "critic_params": self.critic_params,
                "target_critic": self.target_critic,
                "wm_opt_state": self.wm_opt_state,
                "actor_opt_state": self.actor_opt_state,
                "critic_opt_state": self.critic_opt_state}

    def set_state(self, state: dict) -> None:
        self.wm_params = state["wm_params"]
        self.actor_params = state["actor_params"]
        self.critic_params = state["critic_params"]
        self.target_critic = state["target_critic"]
        for k in ("wm_opt_state", "actor_opt_state", "critic_opt_state"):
            if k in state:
                setattr(self, k, state[k])


register_algorithm("DreamerV3", DreamerV3)
