"""A2C — synchronous advantage actor-critic.

Counterpart of the reference's `rllib/algorithms/a2c/` (a2c.py:
`training_step` = sample → `train_one_step`; loss `a3c_torch_policy.py`:
plain policy gradient -logp*adv + value loss + entropy bonus). A2C is the
degenerate PPO: one pass over fresh on-policy data with no ratio clipping
(the importance ratio is 1 on the first visit), so it rides PPO's compiled
sample+GAE+update pipeline with clipping disabled and a single epoch —
the same relationship the reference exploits by sharing the A3C loss.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig


class A2CConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lr = 1e-3
        # one full-batch gradient step per iteration, no surrogate clipping
        self.num_sgd_iter = 1
        self.clip_param = 1e9
        self.vf_clip_param = 1e9
        self.sgd_minibatch_size = 10 ** 9   # clamped to batch size
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5


class A2C(PPO):
    _config_class = A2CConfig


register_algorithm("A2C", A2C)
