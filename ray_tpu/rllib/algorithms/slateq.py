"""SlateQ — Q-learning for slate recommendation (Ie et al. 2019).

Counterpart of the reference's `rllib/algorithms/slateq/slateq.py`: the
combinatorial slate action space (choose k of N documents) is made
tractable by SlateQ's decomposition — under a conditional-logit user
choice model, the slate value splits into PER-ITEM Q values weighted by
in-slate click probabilities:

    Q(s, A) = sum_{i in A} v(s,i) * q(s,i) / (v(s,null) + sum_j v(s,j))

so learning reduces to a single-item q(s, i) TD update on the CLICKED
item, and slate selection is the paper's top-k greedy over
v(s,i)*q(s,i) (optimal for unit item sizes).

Ships with `SlateDocEnv`, a synthetic recsys JaxEnv (the reference
validates SlateQ on RecSim's interest-evolution environment the same
way): users carry an interest vector that drifts toward consumed items,
documents are fixed embeddings, clicks follow a conditional logit over
the slate + a null (no-click) option.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import JaxEnv, register_env
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class SlateDocEnv(JaxEnv):
    """Synthetic slate recommendation environment.

    State: user interest vector u in R^d (unit-ish norm). Each step the
    agent shows a slate of k documents out of N fixed embeddings; the
    user clicks document i with probability proportional to
    exp(tau * <u, doc_i>) against a null option exp(tau * null_bias);
    a click pays its engagement reward <u, doc_i> (clipped positive)
    and drifts the interest toward the clicked doc. Observation is the
    user vector concatenated with all doc embeddings (flattened), so a
    per-item q-network can condition on both.
    """

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.n_docs = int(cfg.get("n_docs", 10))
        self.slate_size = int(cfg.get("slate_size", 3))
        self.d = int(cfg.get("embed_dim", 4))
        self.max_steps = int(cfg.get("max_steps", 40))
        self.tau = float(cfg.get("choice_temperature", 2.0))
        self.null_bias = float(cfg.get("null_bias", 0.0))
        self.drift = float(cfg.get("drift", 0.2))
        key = jax.random.PRNGKey(int(cfg.get("doc_seed", 7)))
        docs = jax.random.normal(key, (self.n_docs, self.d))
        self.docs = docs / jnp.linalg.norm(docs, axis=-1, keepdims=True)
        self.observation_space = Box(
            -jnp.inf, jnp.inf, (self.d + self.n_docs * self.d,))
        # the ACTION given to step() is the slate: [k] int32 doc indices
        self.action_space = Box(0, self.n_docs - 1, (self.slate_size,))

    def _obs(self, u):
        return jnp.concatenate([u, self.docs.reshape(-1)])

    def reset(self, key):
        u = jax.random.normal(key, (self.d,))
        u = u / jnp.linalg.norm(u)
        state = {"u": u, "t": jnp.asarray(0, jnp.int32)}
        return state, self._obs(u)

    def step(self, state, action, key):
        slate = jnp.asarray(action, jnp.int32)        # [k]
        u = state["u"]
        k_choice, k_reset = jax.random.split(key)
        affinity = self.docs[slate] @ u               # [k]
        logits = jnp.concatenate(
            [self.tau * affinity, jnp.asarray([self.null_bias])])
        choice = jax.random.categorical(k_choice, logits)   # k = null
        clicked = choice < self.slate_size
        doc_idx = slate[jnp.minimum(choice, self.slate_size - 1)]
        reward = jnp.where(clicked,
                           jnp.maximum(self.docs[doc_idx] @ u, 0.0), 0.0)
        # Interest drift scales with ENGAGEMENT (the positive-part reward),
        # RecSim interest-evolution style: a click on a disliked document
        # is a bounce, not a conversion — without the scaling, showing
        # anti-aligned slates slowly converts the user toward them, which
        # both inverts the incentive and washes out the conditional-logit
        # choice signal the oracle tests assert on.
        new_u = u + self.drift * reward * (self.docs[doc_idx] - u)
        new_u = new_u / jnp.linalg.norm(new_u)
        t = state["t"] + 1
        done = t >= self.max_steps
        reset_state, reset_obs = self.reset(k_reset)
        merged = {"u": jnp.where(done, reset_state["u"], new_u),
                  "t": jnp.where(done, reset_state["t"], t)}
        obs = jnp.where(done, reset_obs, self._obs(new_u))
        info = {"clicked": clicked, "doc": doc_idx}
        return merged, obs, reward, done, info


register_env("SlateDoc", lambda cfg: SlateDocEnv(cfg))


class SlateQConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SlateQ)
        self.lr = 1e-3
        self.gamma = 0.95
        self.train_batch_size = 128
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.n_updates_per_iter = 16
        self.target_network_update_freq = 200
        self.rollout_fragment_length = 16
        self.num_envs_per_worker = 32
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 15_000
        self.hiddens = (64, 64)


class SlateQ(Algorithm):
    _config_class = SlateQConfig

    def setup(self, config: dict) -> None:
        import flax.linen as nn
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not isinstance(self.env, SlateDocEnv):
            raise ValueError("SlateQ requires a SlateDocEnv-style slate "
                             "environment")
        env = self.env
        self._rng = jax.random.PRNGKey(cfg.seed)

        class _ItemQ(nn.Module):
            hiddens: tuple

            @nn.compact
            def __call__(self, user, doc):
                # per-item q(s, i): user state x doc embedding
                x = jnp.concatenate([user, doc, user * doc], axis=-1)
                for h in self.hiddens:
                    x = nn.relu(nn.Dense(h)(x))
                return nn.Dense(1)(x)[..., 0]

        self.qnet = _ItemQ(tuple(cfg.hiddens))
        dummy = jnp.zeros((1, env.d))
        self.params = self.qnet.init(self.next_key(), dummy, dummy)[
            "params"]
        self.build_learner()

    # -- SlateQ mechanics --------------------------------------------------

    def _split_obs(self, obs):
        env = self.env
        user = obs[..., :env.d]
        return user

    def _q_all(self, params, user):
        """q(s, i) for every doc: [B, N]."""
        env = self.env
        b = user.shape[0]
        u = jnp.repeat(user[:, None, :], env.n_docs, axis=1)
        d = jnp.broadcast_to(env.docs[None], (b, env.n_docs, env.d))
        return self.qnet.apply({"params": params},
                               u.reshape(-1, env.d),
                               d.reshape(-1, env.d)).reshape(b,
                                                             env.n_docs)

    def _choice_scores(self, user):
        """v(s, i) = exp(tau <u, doc_i>) for every doc: [B, N]."""
        env = self.env
        return jnp.exp(env.tau * user @ env.docs.T)

    def _greedy_slate(self, params, user):
        """Paper's top-k over v(s,i)*q(s,i) (optimal for unit sizes)."""
        score = self._choice_scores(user) * self._q_all(params, user)
        _, idx = jax.lax.top_k(score, self.env.slate_size)
        return idx.astype(jnp.int32)

    def _slate_value(self, params, user, slate):
        """Q(s, A) under the decomposition."""
        env = self.env
        q = jnp.take_along_axis(self._q_all(params, user), slate, axis=1)
        v = jnp.take_along_axis(self._choice_scores(user), slate, axis=1)
        null = jnp.exp(jnp.asarray(env.null_bias))
        return jnp.sum(v * q, axis=1) / (null + jnp.sum(v, axis=1))

    # -- learner -----------------------------------------------------------

    def build_learner(self) -> None:
        cfg = self.algo_config
        env = self.env
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": jnp.zeros(cfg.num_envs_per_worker),
                       "ep_len": jnp.zeros(cfg.num_envs_per_worker,
                                           jnp.int32)}
        self._sample_fn = jax.jit(self._unroll)
        self._update_fn = jax.jit(self._td_update)
        self._steps = 0
        self._updates = 0
        self._ep_returns: list = []

    def _epsilon(self):
        cfg = self.algo_config
        frac = min(1.0, self._steps / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _unroll(self, params, carry, key, epsilon):
        cfg = self.algo_config
        env = self.env

        def one_step(carry, step_key):
            k_eps, k_rand, k_env = jax.random.split(step_key, 3)
            obs = carry["obs"]
            user = self._split_obs(obs)
            greedy = self._greedy_slate(params, user)      # [B, k]
            rand = jax.random.randint(
                k_rand, greedy.shape, 0, env.n_docs)
            explore = (jax.random.uniform(k_eps, (greedy.shape[0], 1))
                       < epsilon)
            slate = jnp.where(explore, rand, greedy)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, reward, done, info = jax.vmap(env.step)(
                carry["env_state"], slate, env_keys)
            ep_ret = carry["ep_ret"] + reward
            ep_len = carry["ep_len"] + 1
            out = {"obs": obs, "slate": slate, "reward": reward,
                   "done": done, "next_obs": next_obs,
                   "clicked": info["clicked"], "doc": info["doc"],
                   "episode_return": jnp.where(done, ep_ret, jnp.nan)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret),
                         "ep_len": jnp.where(done, 0, ep_len)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        return jax.lax.scan(one_step, carry, keys)

    def _td_update(self, params, target_params, opt_state, batch):
        cfg = self.algo_config
        env = self.env
        user = self._split_obs(batch["obs"])
        next_user = self._split_obs(batch["next_obs"])
        # SlateQ TD target: clicked item's q learns toward the NEXT
        # state's greedy-slate value (eq. 6); no-click transitions carry
        # no item-level gradient (their slate value update is implicit)
        next_slate = self._greedy_slate(target_params, next_user)
        next_v = self._slate_value(target_params, next_user, next_slate)
        nonterm = 1.0 - batch["done"].astype(jnp.float32)
        y = batch["reward"] + cfg.gamma * nonterm * \
            jax.lax.stop_gradient(next_v)
        clicked = batch["clicked"].astype(jnp.float32)

        def loss_fn(p):
            doc_vec = env.docs[batch["doc"].astype(jnp.int32)]
            q_clicked = self.qnet.apply({"params": p}, user, doc_vec)
            per = jnp.square(q_clicked - y) * clicked
            return jnp.sum(per) / jnp.maximum(jnp.sum(clicked), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def training_step(self) -> dict:
        cfg = self.algo_config
        self._carry, traj = self._sample_fn(
            self.params, self._carry, self.next_key(),
            jnp.asarray(self._epsilon()))
        host = {k: np.asarray(v) for k, v in traj.items()}
        n = host["reward"].size
        self._steps += n
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in host.items()
                if k != "episode_return"}
        self.buffer.add_batch(flat)
        rets = host["episode_return"].ravel()
        fin = ~np.isnan(rets)
        self._ep_returns.extend(rets[fin].tolist())
        self._ep_returns = self._ep_returns[-200:]

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = {k: jnp.asarray(v) for k, v in
                         self.buffer.sample(cfg.train_batch_size).items()}
                self.params, self.opt_state, loss = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    batch)
                losses.append(float(loss))
                self._updates += 1
                if self._updates % cfg.target_network_update_freq == 0:
                    self.target_params = jax.tree.map(
                        jnp.copy, self.params)
        if self._ep_returns:
            ep_rew = float(np.mean(self._ep_returns))
        else:
            # No episode finished yet (max_steps can exceed the sampled
            # fragment): extrapolate the in-progress per-step engagement
            # rate to a full episode so iteration 1 still reports a finite
            # random-policy baseline instead of NaN.
            ret = np.asarray(self._carry["ep_ret"], np.float64)
            length = np.asarray(self._carry["ep_len"], np.float64)
            ep_rew = float(ret.sum() / max(length.sum(), 1.0)
                           * self.env.max_steps)
        return {
            "episode_reward_mean": ep_rew,
            "episodes_this_iter": int(fin.sum()),
            "num_env_steps_sampled": self._steps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
        }

    def compute_slate(self, obs) -> np.ndarray:
        user = self._split_obs(jnp.asarray(obs, jnp.float32)[None])
        return np.asarray(self._greedy_slate(self.params, user)[0])

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]


register_algorithm("SlateQ", SlateQ)
