"""Algorithm registry (reference: `rllib/algorithms/registry.py`)."""
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, get_algorithm_class, register_algorithm)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig

__all__ = ["Algorithm", "AlgorithmConfig", "get_algorithm_class",
           "register_algorithm", "PPO", "PPOConfig", "DQN", "DQNConfig",
           "IMPALA", "IMPALAConfig"]
