"""Algorithm registry (reference: `rllib/algorithms/registry.py`)."""
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, get_algorithm_class, register_algorithm)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.marwil import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.ddpg import (
    DDPG, DDPGConfig, TD3, TD3Config)
from ray_tpu.rllib.algorithms.ma_ppo import MAPPOConfig, MultiAgentPPO
from ray_tpu.rllib.algorithms.es import ES, ESConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.bandits import (
    LinTS, LinTSConfig, LinUCB, LinUCBConfig)
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig
from ray_tpu.rllib.algorithms.dt import DT, DTConfig
from ray_tpu.rllib.algorithms.alpha_zero import AlphaZero, AlphaZeroConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig
from ray_tpu.rllib.algorithms.pg import PG, PGConfig
from ray_tpu.rllib.algorithms.slateq import SlateQ, SlateQConfig
from ray_tpu.rllib.algorithms.simple_q import (
    A3C, A3CConfig, SimpleQ, SimpleQConfig)

__all__ = ["Algorithm", "AlgorithmConfig", "get_algorithm_class",
           "register_algorithm", "PPO", "PPOConfig", "DQN", "DQNConfig",
           "IMPALA", "IMPALAConfig", "A2C", "A2CConfig",
           "APPO", "APPOConfig", "SAC", "SACConfig",
           "BC", "BCConfig", "MARWIL", "MARWILConfig",
           "CQL", "CQLConfig", "DDPG", "DDPGConfig", "TD3", "TD3Config",
           "MultiAgentPPO", "MAPPOConfig", "ES", "ESConfig",
           "LinUCB", "LinUCBConfig", "LinTS", "LinTSConfig",
           "ApexDQN", "ApexDQNConfig", "R2D2", "R2D2Config",
           "QMIX", "QMIXConfig", "DT", "DTConfig",
           "AlphaZero", "AlphaZeroConfig",
           "DreamerV3", "DreamerV3Config",
           "MADDPG", "MADDPGConfig", "ARS", "ARSConfig",
           "CRR", "CRRConfig", "PG", "PGConfig",
           "SlateQ", "SlateQConfig", "SimpleQ", "SimpleQConfig",
           "A3C", "A3CConfig"]
