"""Ape-X DQN: distributed-replay DQN over actor rollout workers.

Counterpart of the reference's `rllib/algorithms/apex_dqn/` (Horgan et
al. 2018): N rollout actors explore with PER-ACTOR epsilons
(eps_i = eps^(1 + alpha * i / (N-1)), the paper's diversity schedule),
their experience round-robins into a fleet of SHARDED prioritized
replay actors (reference: `apex_dqn.py:328-337` ReplayActor fleet), and
the learner pipelines sampled batches — the next shard's sample is in
flight while the current batch trains — feeding updated priorities back
to the shard that served each batch. The TD update and target-network
machinery are DQN's own jitted functions; what Ape-X adds is the actor
fan-out, sharded replay, and the priority feedback loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.worker_set import WorkerSet, merge_episode_stats


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_rollout_workers = 2
        self.rollout_fragment_length = 64
        self.prioritized_replay = True
        # per-actor exploration diversity (Ape-X paper section 3)
        self.exploration_epsilon_base = 0.4
        self.exploration_epsilon_alpha = 7.0
        self.n_updates_per_iter = 32
        self.learning_starts = 500
        # replay shards as ACTORS (reference: apex ReplayActor fleet) —
        # ingest/sampling scale with shards instead of funneling through
        # the learner process
        self.num_replay_shards = 2


class _EpsilonPolicy:
    """QModule shim fixing this actor's epsilon so the shared
    PythonEnvRunner (which calls compute_actions(params, obs, key))
    explores at the Ape-X per-actor rate."""

    def __init__(self, module, epsilon: float):
        self._module = module
        self.epsilon = epsilon
        self.observation_space = module.observation_space
        self.action_space = module.action_space

    def init(self, key):
        return self._module.init(key)

    def compute_actions(self, params, obs, key, explore: bool = True):
        actions, q_sel, q = self._module.compute_actions(
            params, obs, key, epsilon=self.epsilon if explore else 0.0)
        # the shared runner expects (actions, logp-like, SCALAR value);
        # max-Q plays the value role (only TD training consumes it here)
        return actions, q_sel, q.max(axis=-1)

    def forward(self, params, obs):
        """Bootstrap seam for the vectorized runner (InGraphSampler
        calls module.forward for the fragment-end value): max-Q plays
        the state value."""
        q = self._module.q_values(params, obs)
        return None, q.max(axis=-1)


class ApexDQN(DQN):
    _config_class = ApexDQNConfig

    def setup(self, config: dict) -> None:
        # DQN.setup insists on a JaxEnv for its in-graph sampler; Ape-X
        # samples through actor workers instead, so build the pieces
        # directly.
        cfg = self.algo_config
        from ray_tpu.rllib.core.rl_module import QModule
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        self.module = QModule(self.env.observation_space,
                              self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, k = jax.random.split(self._rng)
        self.params = self.module.init(k)
        self.build_learner()

    def _actor_epsilon(self, i: int) -> float:
        cfg = self.algo_config
        n = max(1, cfg.num_rollout_workers)
        frac = i / max(1, n - 1)
        return float(cfg.exploration_epsilon_base
                     ** (1.0 + cfg.exploration_epsilon_alpha * frac))

    def build_learner(self) -> None:
        import optax
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        # SHARDED replay: one ReplayActor per shard; adds round-robin
        # from collection, samples round-robin into the learner, and
        # priorities flow back to the shard that served the batch
        import ray_tpu as _rt
        from ray_tpu.rllib.replay_buffers import ReplayActor
        n_shards = max(1, cfg.num_replay_shards)
        shard_cap = max(1, cfg.buffer_size // n_shards)
        actor_cls = _rt.remote(num_cpus=0)(ReplayActor)
        self.replay_shards = [
            actor_cls.remote(shard_cap, cfg.prioritized_replay_alpha,
                             cfg.prioritized_replay_beta,
                             seed=cfg.seed + i,
                             prioritized=cfg.prioritized_replay)
            for i in range(n_shards)]
        self._add_rr = 0
        self._sample_rr = 0
        self._pending_adds: list = []
        self._steps_sampled = 0
        self._num_updates = 0
        self._last_target_update = 0
        self._update_fn = jax.jit(self._td_update)
        import threading
        self._act_lock = threading.Lock()

        env_spec, env_cfg = cfg.env, dict(cfg.env_config)
        model_cfg = dict(cfg.model)
        eps = [self._actor_epsilon(i)
               for i in range(max(1, cfg.num_rollout_workers))]

        def env_creator(worker_index, _s=env_spec, _c=env_cfg):
            from ray_tpu.rllib.env.jax_env import make_env
            return make_env(_s, _c)

        def module_creator(env, worker_index=0, _mc=model_cfg,
                           _eps=eps):
            from ray_tpu.rllib.core.rl_module import QModule
            q = QModule(env.observation_space, env.action_space, _mc)
            return _EpsilonPolicy(
                q, _eps[min(worker_index, len(_eps) - 1)])

        self.workers = WorkerSet(
            max(1, cfg.num_rollout_workers), env_creator,
            module_creator, cfg.rollout_fragment_length, seed=cfg.seed,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            connectors=cfg.connector_dict(),
            num_envs_per_worker=cfg.num_envs_per_worker)

    def cleanup(self) -> None:
        import ray_tpu as _rt
        super().cleanup()
        # the replay fleet is ours: without this, repeated build/cleanup
        # cycles (tune sweeps) accumulate dead shard actors + buffers
        for s in getattr(self, "replay_shards", []):
            try:
                _rt.kill(s)
            except Exception:
                pass
        self.replay_shards = []

    def training_step(self) -> dict:
        import ray_tpu as _rt
        cfg = self.algo_config
        n_shards = len(self.replay_shards)
        batches, _last_vals, stats_list = self.workers.sample_all(
            self.params)
        # backpressure from LAST round's adds (one round in flight keeps
        # collection and shard ingest overlapped without unbounded queues)
        if self._pending_adds:
            _rt.get(self._pending_adds, timeout=300)
        self._pending_adds = []
        t_dim = np.asarray(batches[0][sb.REWARDS]).ndim if batches else 1
        for batch in batches:
            # vectorized workers return time-major [T, B, ...]; replay
            # ingests flat 1-step transitions
            flat = {}
            for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                      sb.NEXT_OBS):
                v = np.asarray(batch[k])
                if t_dim == 2:
                    v = v.reshape((-1,) + v.shape[2:])
                flat[k] = v
            shard = self.replay_shards[self._add_rr % n_shards]
            self._add_rr += 1
            self._pending_adds.append(shard.add_batch.remote(flat))
            self._steps_sampled += len(flat[sb.OBS])

        sizes = _rt.get([s.size.remote() for s in self.replay_shards],
                        timeout=300)
        losses = []
        if sum(sizes) >= cfg.learning_starts:
            # pipeline: next shard's sample is in flight while the
            # learner updates on the current batch
            def req():
                shard_i = self._sample_rr % n_shards
                self._sample_rr += 1
                shard = self.replay_shards[shard_i]
                return shard_i, shard.sample.remote(cfg.train_batch_size)
            inflight = req()
            for i in range(cfg.n_updates_per_iter):
                shard_i, ref = inflight
                batch = _rt.get(ref, timeout=300)
                # prefetch ONLY while iterations remain: a trailing
                # request would serialize a whole batch just to discard
                inflight = (req() if i + 1 < cfg.n_updates_per_iter
                            else None)
                if batch is None:       # shard still filling
                    continue
                device_batch = {k: jnp.asarray(v)
                                for k, v in batch.items()
                                if k != "batch_indexes"}
                self.params, self.opt_state, loss, td = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    device_batch)
                losses.append(float(loss))
                self._num_updates += 1
                if cfg.prioritized_replay:
                    # fire-and-forget back to the OWNING shard
                    self.replay_shards[shard_i].update_priorities.remote(
                        batch["batch_indexes"], np.asarray(td))
                if (self._num_updates - self._last_target_update
                        >= cfg.target_network_update_freq):
                    self.target_params = jax.tree.map(
                        jnp.copy, self.params)
                    self._last_target_update = self._num_updates

        metrics = merge_episode_stats(stats_list) if stats_list else {}
        metrics.update({
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": int(sum(sizes)),
            "replay_shard_sizes": [int(s) for s in sizes],
            "actor_epsilons": [
                self._actor_epsilon(i)
                for i in range(max(1, cfg.num_rollout_workers))],
        })
        metrics.setdefault("episode_reward_mean", float("nan"))
        return metrics

register_algorithm("ApexDQN", ApexDQN)
