"""Ape-X DQN: distributed-replay DQN over actor rollout workers.

Counterpart of the reference's `rllib/algorithms/apex_dqn/` (Horgan et
al. 2018): N rollout actors explore with PER-ACTOR epsilons
(eps_i = eps^(1 + alpha * i / (N-1)), the paper's diversity schedule),
their experience lands in one central prioritized replay buffer, and
the learner takes many TD-update steps per collection round, feeding
updated priorities back. The TD update and target-network machinery are
DQN's own jitted functions; what Ape-X adds is the actor fan-out and
priority feedback loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer
from ray_tpu.rllib.worker_set import WorkerSet, merge_episode_stats


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_rollout_workers = 2
        self.rollout_fragment_length = 64
        self.prioritized_replay = True
        # per-actor exploration diversity (Ape-X paper section 3)
        self.exploration_epsilon_base = 0.4
        self.exploration_epsilon_alpha = 7.0
        self.n_updates_per_iter = 32
        self.learning_starts = 500


class _EpsilonPolicy:
    """QModule shim fixing this actor's epsilon so the shared
    PythonEnvRunner (which calls compute_actions(params, obs, key))
    explores at the Ape-X per-actor rate."""

    def __init__(self, module, epsilon: float):
        self._module = module
        self.epsilon = epsilon
        self.observation_space = module.observation_space
        self.action_space = module.action_space

    def init(self, key):
        return self._module.init(key)

    def compute_actions(self, params, obs, key, explore: bool = True):
        actions, q_sel, q = self._module.compute_actions(
            params, obs, key, epsilon=self.epsilon if explore else 0.0)
        # the shared runner expects (actions, logp-like, SCALAR value);
        # max-Q plays the value role (only TD training consumes it here)
        return actions, q_sel, q.max(axis=-1)


class ApexDQN(DQN):
    _config_class = ApexDQNConfig

    def setup(self, config: dict) -> None:
        # DQN.setup insists on a JaxEnv for its in-graph sampler; Ape-X
        # samples through actor workers instead, so build the pieces
        # directly.
        cfg = self.algo_config
        from ray_tpu.rllib.core.rl_module import QModule
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        self.module = QModule(self.env.observation_space,
                              self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, k = jax.random.split(self._rng)
        self.params = self.module.init(k)
        self.build_learner()

    def _actor_epsilon(self, i: int) -> float:
        cfg = self.algo_config
        n = max(1, cfg.num_rollout_workers)
        frac = i / max(1, n - 1)
        return float(cfg.exploration_epsilon_base
                     ** (1.0 + cfg.exploration_epsilon_alpha * frac))

    def build_learner(self) -> None:
        import optax
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        if cfg.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                cfg.buffer_size, cfg.prioritized_replay_alpha,
                cfg.prioritized_replay_beta, seed=cfg.seed)
        else:
            from ray_tpu.rllib.replay_buffers import ReplayBuffer
            self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._steps_sampled = 0
        self._num_updates = 0
        self._last_target_update = 0
        self._update_fn = jax.jit(self._td_update)
        import threading
        self._act_lock = threading.Lock()

        env_spec, env_cfg = cfg.env, dict(cfg.env_config)
        model_cfg = dict(cfg.model)
        eps = [self._actor_epsilon(i)
               for i in range(max(1, cfg.num_rollout_workers))]

        def env_creator(worker_index, _s=env_spec, _c=env_cfg):
            from ray_tpu.rllib.env.jax_env import make_env
            return make_env(_s, _c)

        def module_creator(env, worker_index=0, _mc=model_cfg,
                           _eps=eps):
            from ray_tpu.rllib.core.rl_module import QModule
            q = QModule(env.observation_space, env.action_space, _mc)
            return _EpsilonPolicy(
                q, _eps[min(worker_index, len(_eps) - 1)])

        self.workers = WorkerSet(
            max(1, cfg.num_rollout_workers), env_creator,
            module_creator, cfg.rollout_fragment_length, seed=cfg.seed,
            num_cpus_per_worker=cfg.num_cpus_per_worker,
            connectors=cfg.connector_dict())

    def training_step(self) -> dict:
        cfg = self.algo_config
        batches, _last_vals, stats_list = self.workers.sample_all(
            self.params)
        for batch in batches:
            flat = {k: np.asarray(batch[k])
                    for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                              sb.NEXT_OBS)}
            self.buffer.add_batch(flat)
            self._steps_sampled += len(flat[sb.OBS])

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                device_batch = {k: jnp.asarray(v)
                                for k, v in batch.items()
                                if k != "batch_indexes"}
                self.params, self.opt_state, loss, td = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    device_batch)
                losses.append(float(loss))
                self._num_updates += 1
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        batch["batch_indexes"], np.asarray(td))
                if (self._num_updates - self._last_target_update
                        >= cfg.target_network_update_freq):
                    self.target_params = jax.tree.map(
                        jnp.copy, self.params)
                    self._last_target_update = self._num_updates

        metrics = merge_episode_stats(stats_list) if stats_list else {}
        metrics.update({
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
            "actor_epsilons": [
                self._actor_epsilon(i)
                for i in range(max(1, cfg.num_rollout_workers))],
        })
        metrics.setdefault("episode_reward_mean", float("nan"))
        return metrics

register_algorithm("ApexDQN", ApexDQN)
