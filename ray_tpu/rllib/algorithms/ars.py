"""ARS — augmented random search (Mania et al. 2018).

Counterpart of the reference's `rllib/algorithms/ars/ars.py` (a
CPU-worker fleet evaluating random perturbations). What distinguishes
ARS from ES (the "augmentations", §3 of the paper):

- top-b DIRECTION SELECTION: only the b best directions (by
  max(r+, r-)) contribute to the update;
- the step is scaled by the STD of the selected returns (sigma_R), not
  a rank transform;
- observations are WHITENED by running mean/std collected during
  rollouts (V2), so the linear-ish policies the paper uses see
  normalized state.

TPU-native shape, like our ES: the whole population of antithetic
perturbations and all their rollouts run as ONE vmapped, scanned,
jitted program — the paper's parallel CPU fleet becomes a single
compiled evaluation.
"""

from __future__ import annotations

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.jax_env import is_jax_env


class ARSConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.lr = 0.02                    # paper: step size alpha
        self.num_directions = 32          # antithetic pairs per iter
        self.top_directions = 16          # b <= num_directions
        self.noise_stdev = 0.05
        self.episode_horizon = 200
        self.observation_filter = True    # V2 obs whitening
        self.model = {"fcnet_hiddens": (32,)}


class ARS(Algorithm):
    _config_class = ARSConfig

    def setup(self, config: dict) -> None:
        super().setup(config)
        if not is_jax_env(self.env):
            raise ValueError("ARS requires a JaxEnv (in-graph rollouts)")
        cfg = self.algo_config
        if cfg.top_directions > cfg.num_directions:
            raise ValueError("top_directions must be <= num_directions")

    def build_learner(self) -> None:
        cfg = self.algo_config
        self._flat, self._unravel = jax.flatten_util.ravel_pytree(
            self.params)
        obs_dim = tuple(self.env.observation_space.shape)
        # running whitening stats (count, mean, M2) — Welford form so
        # merging a rollout's batch stats is exact
        self._obs_stats = (jnp.asarray(1e-4), jnp.zeros(obs_dim),
                           1e-4 * jnp.ones(obs_dim))   # sigma starts ~1
        self._step_fn = jax.jit(self._ars_step)
        self._iter = 0

    # -- one perturbed policy's return + obs-moment accumulation ----------

    def _episode_return(self, flat_params, key, mu, sigma):
        cfg = self.algo_config
        params = self._unravel(flat_params)
        k_reset, k_run = jax.random.split(key)
        state, obs = self.env.reset(k_reset)

        def step(carry, k):
            state, obs, ret, alive, cnt, s1, s2 = carry
            w = (obs - mu) / sigma if cfg.observation_filter else obs
            actions, _, _ = self.module.compute_actions(
                params, w[None], k, explore=False)
            state, obs2, r, done, _ = self.env.step(
                state, jnp.squeeze(actions, 0), k)
            # fitness + whitening stats stop at the FIRST termination
            # (the env auto-resets; see es.py for why the mask matters)
            ret = ret + r * alive
            cnt = cnt + alive
            s1 = s1 + obs * alive
            s2 = s2 + obs * obs * alive
            alive = alive * (1.0 - done.astype(jnp.float32))
            return (state, obs2, ret, alive, cnt, s1, s2), None

        zeros = jnp.zeros_like(obs)
        keys = jax.random.split(k_run, cfg.episode_horizon)
        (_, _, ret, _, cnt, s1, s2), _ = jax.lax.scan(
            step, (state, obs, 0.0, 1.0, 0.0, zeros, zeros), keys)
        return ret, (cnt, s1, s2)

    def _ars_step(self, flat, obs_stats, key):
        cfg = self.algo_config
        n, b = cfg.num_directions, cfg.top_directions
        k_noise, k_eval = jax.random.split(key)
        delta = jax.random.normal(k_noise, (n, flat.shape[0]),
                                  dtype=flat.dtype)
        eval_keys = jax.random.split(k_eval, n)
        cnt0, mu, m2 = obs_stats
        sigma = jnp.sqrt(jnp.maximum(m2 / jnp.maximum(cnt0, 1.0), 1e-6))

        run = jax.vmap(self._episode_return, in_axes=(0, 0, None, None))
        r_plus, st_p = run(flat[None, :] + cfg.noise_stdev * delta,
                           eval_keys, mu, sigma)
        r_minus, st_m = run(flat[None, :] - cfg.noise_stdev * delta,
                            eval_keys, mu, sigma)

        # top-b directions by best-of-pair performance (paper alg. 2,
        # line 6)
        scores = jnp.maximum(r_plus, r_minus)
        _, top = jax.lax.top_k(scores, b)
        rp, rm = r_plus[top], r_minus[top]
        sigma_r = jnp.std(jnp.concatenate([rp, rm])) + 1e-8
        update = (cfg.lr / (b * sigma_r)) * ((rp - rm) @ delta[top])
        flat = flat + update

        # merge whitening moments from every rollout (plain sums)
        cnt = cnt0 + jnp.sum(st_p[0]) + jnp.sum(st_m[0])
        s1 = (mu * cnt0 + jnp.sum(st_p[1], 0) + jnp.sum(st_m[1], 0))
        s2 = (m2 + mu * mu * cnt0
              + jnp.sum(st_p[2], 0) + jnp.sum(st_m[2], 0))
        new_mu = s1 / cnt
        new_m2 = s2 - new_mu * new_mu * cnt
        stats = {
            "episode_reward_mean": jnp.mean(
                jnp.concatenate([r_plus, r_minus])),
            "episode_reward_max": jnp.maximum(jnp.max(r_plus),
                                              jnp.max(r_minus)),
            "sigma_r": sigma_r,
        }
        return flat, (cnt, new_mu, new_m2), stats

    def training_step(self) -> dict:
        self._flat, self._obs_stats, stats = self._step_fn(
            self._flat, self._obs_stats, self.next_key())
        self._iter += 1
        self.params = self._unravel(self._flat)
        return {
            "episode_reward_mean": float(stats["episode_reward_mean"]),
            "episode_reward_max": float(stats["episode_reward_max"]),
            "sigma_r": float(stats["sigma_r"]),
            "episodes_this_iter": 2 * self.algo_config.num_directions,
            "training_iteration": self._iter,
        }

    def compute_single_action(self, obs, explore: bool = False):
        cnt, mu, m2 = self._obs_stats
        sigma = jnp.sqrt(jnp.maximum(m2 / jnp.maximum(cnt, 1.0), 1e-6))
        w = (jnp.asarray(obs) - mu) / sigma \
            if self.algo_config.observation_filter else jnp.asarray(obs)
        actions, _, _ = self.module.compute_actions(
            self.params, w[None], self.next_key(), explore=explore)
        a = np.asarray(actions)[0]
        return a.item() if a.ndim == 0 else a

    def get_state(self) -> dict:
        return {"params": self.params,
                "flat": np.asarray(self._flat),
                "obs_stats": jax.tree.map(np.asarray, self._obs_stats)}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self._flat = jnp.asarray(state["flat"])
        self._obs_stats = tuple(
            jnp.asarray(x) for x in state["obs_stats"])


register_algorithm("ARS", ARS)
