"""SAC — soft actor-critic for continuous control.

Counterpart of the reference's `rllib/algorithms/sac/` (sac.py config;
loss `sac_torch_policy.py` actor_critic_loss: squashed-Gaussian policy,
twin Q with min-target, entropy temperature alpha auto-tuned toward
-|A| target entropy, polyak target updates). The rollout fragment is
compiled (vmap env + scan with reparameterized sampling inside the graph);
replay is host-side; actor/critic/alpha updates are one fused jit.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.algorithms.off_policy import (
    QNet as _QNet, drain_episode_returns, scale_action,
    stack_replay_batches)
from ray_tpu.rllib.env.jax_env import is_jax_env, make_env
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


class _SquashedActor(nn.Module):
    act_dim: int
    hiddens: Tuple[int, ...] = (256, 256)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.act_dim)(x)
        log_std = jnp.clip(nn.Dense(self.act_dim)(x),
                           _LOG_STD_MIN, _LOG_STD_MAX)
        return mean, log_std


def _sample_squashed(mean, log_std, key):
    """Reparameterized tanh-Gaussian sample + its log-prob
    (the change-of-variables correction from sac_torch_policy.py)."""
    eps = jax.random.normal(key, mean.shape)
    pre = mean + jnp.exp(log_std) * eps
    act = jnp.tanh(pre)
    var = jnp.exp(2 * log_std)
    logp_gauss = jnp.sum(
        -0.5 * ((pre - mean) ** 2 / var + 2 * log_std
                + jnp.log(2 * jnp.pi)), axis=-1)
    # log det of tanh: sum log(1 - tanh^2); the numerically-stable form
    logp = logp_gauss - jnp.sum(
        2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)), axis=-1)
    return act, logp


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.buffer_size = 100_000
        self.learning_starts = 1500
        self.tau = 0.005                  # polyak
        # Treat episode ends as time-limit truncations and bootstrap the Q
        # target through them (reference sac.py `no_done_at_end`). Caveat,
        # same as the reference with auto-reset envs: NEXT_OBS on a done
        # row is the next episode's reset obs, so the bootstrap uses
        # V(reset_state) — an approximation that is right on average when
        # reset states are representative (e.g. Pendulum's random starts).
        self.no_done_at_end = False
        self.initial_alpha = 1.0
        self.target_entropy = None        # default -act_dim
        self.n_updates_per_iter = 32
        self.rollout_fragment_length = 8
        self.num_envs_per_worker = 16
        self.model = {"fcnet_hiddens": (256, 256)}


class SAC(Algorithm):
    _config_class = SACConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_jax_env(self.env):
            raise ValueError("SAC v1 requires a JaxEnv (in-graph sampler)")
        if not isinstance(self.env.action_space, Box):
            raise ValueError("SAC requires a continuous (Box) action space")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.build_learner()

    def _build_networks(self) -> None:
        """Nets, params, targets, optimizer, buffer — the learner half,
        shared with offline subclasses (CQL) that never roll out."""
        cfg = self.algo_config
        obs_dim = int(np.prod(self.env.observation_space.shape))
        self._act_dim = int(np.prod(self.env.action_space.shape))
        self._act_low = jnp.asarray(self.env.action_space.low)
        self._act_high = jnp.asarray(self.env.action_space.high)
        hiddens = tuple(cfg.model.get("fcnet_hiddens", (256, 256)))
        self.actor = _SquashedActor(self._act_dim, hiddens)
        self.q1 = _QNet(hiddens)
        self.q2 = _QNet(hiddens)
        dummy_o = jnp.zeros((1, obs_dim))
        dummy_a = jnp.zeros((1, self._act_dim))
        k1, k2, k3 = jax.random.split(self.next_key(), 3)
        self.params = {
            "actor": self.actor.init(k1, dummy_o),
            "q1": self.q1.init(k2, dummy_o, dummy_a),
            "q2": self.q2.init(k3, dummy_o, dummy_a),
            "log_alpha": jnp.log(jnp.asarray(cfg.initial_alpha)),
        }
        self.target_q = {"q1": jax.tree.map(jnp.copy, self.params["q1"]),
                         "q2": jax.tree.map(jnp.copy, self.params["q2"])}
        self._target_entropy = (cfg.target_entropy
                                if cfg.target_entropy is not None
                                else -float(self._act_dim))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._steps_sampled = 0
        # K updates fused into ONE dispatch (lax.scan over stacked
        # batches): per-update Python dispatch on a dependent chain costs
        # ~6x the actual compute, and on TPU the fused form keeps the whole
        # inner loop resident on the chip
        self._update_many_fn = jax.jit(self._update_many)

    def build_learner(self) -> None:
        cfg = self.algo_config
        self._build_networks()
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": jnp.zeros(cfg.num_envs_per_worker)}
        self._sample_fn = jax.jit(self._sample_impl)
        self._ep_returns: list = []

    def _scale_action(self, act_tanh):
        """[-1,1] -> env bounds."""
        return scale_action(self._act_low, self._act_high, act_tanh)

    # -- compiled rollout ----------------------------------------------------

    def _sample_impl(self, params, carry, key):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            mean, log_std = self.actor.apply(params["actor"], obs)
            act, _ = _sample_squashed(mean, log_std, k_act)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], self._scale_action(act), env_keys)
            ep_ret = carry["ep_ret"] + reward
            out = {sb.OBS: obs, sb.ACTIONS: act, sb.REWARDS: reward,
                   sb.NEXT_OBS: next_obs, sb.DONES: done,
                   "episode_return": jnp.where(done, ep_ret, jnp.nan)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        return jax.lax.scan(one_step, carry, keys)

    # -- fused actor/critic/alpha update ------------------------------------

    def _sac_update(self, params, target_q, opt_state, batch, key,
                    extra_loss=None):
        """One SAC step. `extra_loss(p, batch, key) -> scalar` lets
        subclasses add a regularizer (CQL) without duplicating the fused
        actor/critic/alpha loss."""
        cfg = self.algo_config
        k_next, k_pi, k_extra = jax.random.split(key, 3)

        def loss_fn(p):
            alpha = jnp.exp(p["log_alpha"])
            # critic target: min of target twins on next action from the
            # CURRENT policy, minus entropy term
            mean_n, log_std_n = self.actor.apply(p["actor"],
                                                 batch[sb.NEXT_OBS])
            act_n, logp_n = _sample_squashed(mean_n, log_std_n, k_next)
            tq1 = self.q1.apply(target_q["q1"], batch[sb.NEXT_OBS], act_n)
            tq2 = self.q2.apply(target_q["q2"], batch[sb.NEXT_OBS], act_n)
            if cfg.no_done_at_end:
                nonterm = jnp.ones_like(batch[sb.REWARDS])
            else:
                nonterm = 1.0 - batch[sb.DONES].astype(jnp.float32)
            target = batch[sb.REWARDS] + cfg.gamma * nonterm * \
                jax.lax.stop_gradient(
                    jnp.minimum(tq1, tq2)
                    - jax.lax.stop_gradient(alpha) * logp_n)
            q1 = self.q1.apply(p["q1"], batch[sb.OBS], batch[sb.ACTIONS])
            q2 = self.q2.apply(p["q2"], batch[sb.OBS], batch[sb.ACTIONS])
            critic_loss = jnp.mean((q1 - target) ** 2) + \
                jnp.mean((q2 - target) ** 2)
            # actor: maximize min-Q of fresh action + entropy
            mean_c, log_std_c = self.actor.apply(p["actor"], batch[sb.OBS])
            act_c, logp_c = _sample_squashed(mean_c, log_std_c, k_pi)
            q_pi = jnp.minimum(
                self.q1.apply(jax.lax.stop_gradient(p["q1"]),
                              batch[sb.OBS], act_c),
                self.q2.apply(jax.lax.stop_gradient(p["q2"]),
                              batch[sb.OBS], act_c))
            actor_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp_c - q_pi)
            # temperature toward target entropy
            alpha_loss = -jnp.mean(
                p["log_alpha"]
                * jax.lax.stop_gradient(logp_c + self._target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            if extra_loss is not None:
                total = total + extra_loss(p, batch, k_extra)
            return total, (critic_loss, actor_loss, alpha)

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_q = jax.tree.map(
            lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
            target_q, {"q1": params["q1"], "q2": params["q2"]})
        return params, target_q, opt_state, loss, aux

    # subclass hook: CQL swaps in its regularized update
    def _one_update(self, params, target_q, opt_state, batch, key):
        return self._sac_update(params, target_q, opt_state, batch, key)

    def _update_many(self, params, target_q, opt_state, batches, key):
        """lax.scan over [K, B, ...] stacked replay batches."""
        keys = jax.random.split(key, batches[sb.REWARDS].shape[0])

        def one(state, xs):
            params, target_q, opt_state = state
            batch, k = xs
            params, target_q, opt_state, loss, aux = self._one_update(
                params, target_q, opt_state, batch, k)
            return (params, target_q, opt_state), (loss, aux[2])

        (params, target_q, opt_state), (losses, alphas) = jax.lax.scan(
            one, (params, target_q, opt_state), (batches, keys))
        return params, target_q, opt_state, losses, alphas

    def _sample_update_batches(self, k: int):
        return stack_replay_batches(self.buffer, k,
                                    self.algo_config.train_batch_size)

    # ------------------------------------------------------------------------

    def training_step(self) -> dict:
        cfg = self.algo_config
        self._carry, traj = self._sample_fn(
            self.params, self._carry, self.next_key())
        host = {k: np.asarray(v) for k, v in traj.items()}
        flat = drain_episode_returns(host, self._ep_returns)
        self.buffer.add_batch(flat)
        self._steps_sampled += len(flat[sb.REWARDS])

        losses, alphas = [], []
        if len(self.buffer) >= cfg.learning_starts:
            batches = self._sample_update_batches(cfg.n_updates_per_iter)
            (self.params, self.target_q, self.opt_state, loss_v,
             alpha_v) = self._update_many_fn(
                self.params, self.target_q, self.opt_state, batches,
                self.next_key())
            losses = np.asarray(loss_v).tolist()
            alphas = np.asarray(alpha_v).tolist()
        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "alpha": float(np.mean(alphas)) if alphas else float("nan"),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def compute_single_action(self, obs, explore: bool = False):
        obs = jnp.asarray(obs)[None]
        mean, log_std = self.actor.apply(self.params["actor"], obs)
        if explore:
            act, _ = _sample_squashed(mean, log_std, self.next_key())
        else:
            act = jnp.tanh(mean)
        return np.asarray(self._scale_action(act))[0]

    def get_state(self) -> dict:
        return {"params": self.params, "target_q": self.target_q,
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_q = state["target_q"]
        self.opt_state = state["opt_state"]


register_algorithm("SAC", SAC)
