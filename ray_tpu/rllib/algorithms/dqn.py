"""DQN — deep Q-learning with target network, double-Q, and optional
prioritized replay.

Counterpart of the reference's `rllib/algorithms/dqn/` (dqn.py
training_step: sample → store → replay → train → target-update;
loss `dqn_torch_policy.py` build_q_losses: double-Q + huber). The
sampling fragment is compiled (vmap env + scan, epsilon-greedy inside the
graph); replay lives host-side; the TD update is a jitted function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.core.rl_module import QModule
from ray_tpu.rllib.env.jax_env import is_jax_env
from ray_tpu.rllib.replay_buffers import (
    PrioritizedReplayBuffer, ReplayBuffer)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.buffer_size = 50_000
        self.learning_starts = 1000
        # Gradient updates between target-network syncs. Deliberate unit
        # change vs the reference (env steps, dqn.py config): the compiled
        # vectorized sampler produces steps orders of magnitude faster
        # than a Python env loop, so step-based sync gives too few fitted
        # regression updates per Bellman backup and Q ratchets upward
        # (deadly triad). Update-based sync is invariant to sampling rate.
        self.target_network_update_freq = 500
        self.double_q = True
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.n_updates_per_iter = 64
        # Multi-step TD backup (reference: dqn.py `n_step`; Ape-X uses 3).
        # Each stored transition carries the k-step discounted return, the
        # observation k steps ahead, and an explicit per-sample discount
        # gamma^k * nonterminal (k <= n_step, truncating at episode or
        # fragment end). Value then propagates k steps per target sync
        # instead of one — the difference between learning and stalling
        # when the update budget only affords a handful of syncs.
        self.n_step = 1
        # epsilon-greedy linear schedule, in env steps
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 25_000
        self.rollout_fragment_length = 16
        self.num_envs_per_worker = 32
        self.model = {"fcnet_hiddens": (64, 64),
                      "fcnet_activation": "relu"}
        # External experience source instead of the in-graph sampler: a
        # callable returning a SampleBatch-like dict per iteration, or an
        # object with .next() (JsonReader, PolicyServerInput.next_batch —
        # the reference's policy_server_input.py client-server RL path).
        # `env` is then only consulted for observation/action spaces.
        self.input_ = None

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self


def _n_step_fragment(host: dict, n: int, gamma: float) -> dict:
    """Fold a sampled [T, B] fragment into n-step transitions.

    REWARDS becomes the k-step discounted return, NEXT_OBS the
    observation k steps ahead, and a new "discounts" column carries
    gamma^k * nonterminal, where k <= n truncates at episode end (done)
    or fragment end. OBS/ACTIONS stay at the transition start. The TD
    update consumes "discounts" directly, so no gamma bookkeeping leaks
    into the loss."""
    src_next = np.asarray(host[sb.NEXT_OBS])
    r = np.asarray(host[sb.REWARDS], np.float32)
    d = np.asarray(host[sb.DONES], bool)
    ret = r.copy()
    next_obs = src_next.copy()
    disc = gamma * (~d).astype(np.float32)
    t_len = r.shape[0]
    for i in range(1, n):
        for t in range(t_len - i):
            cont = disc[t] != 0.0
            ret[t] = np.where(cont, ret[t] + disc[t] * r[t + i], ret[t])
            next_obs[t][cont] = src_next[t + i][cont]
            disc[t] = np.where(cont,
                               disc[t] * gamma * (~d[t + i]), disc[t])
    out = dict(host)
    out[sb.REWARDS] = ret
    out[sb.NEXT_OBS] = next_obs
    # dones stays the per-step flag (episode accounting); the bootstrap
    # mask lives entirely in "discounts".
    out["discounts"] = disc
    return out


class DQN(Algorithm):
    _config_class = DQNConfig

    def setup(self, config: dict) -> None:
        # QModule instead of the policy-gradient RLModule.
        cfg = self.algo_config
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if cfg.input_ is None and not is_jax_env(self.env):
            raise ValueError(
                "DQN v1 requires a JaxEnv (in-graph sampler); wrap python "
                "envs, use PPO's WorkerSet path, or feed external "
                "experience via offline_data(input_=...)")
        self.module = QModule(self.env.observation_space,
                              self.env.action_space, cfg.model)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._rng, k = jax.random.split(self._rng)
        self.params = self.module.init(k)
        self.build_learner()

    def build_learner(self) -> None:
        import threading
        cfg = self.algo_config
        self._act_lock = threading.Lock()
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        if cfg.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                cfg.buffer_size, cfg.prioritized_replay_alpha,
                cfg.prioritized_replay_beta, seed=cfg.seed)
        else:
            self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._steps_sampled = 0
        self._num_updates = 0
        self._last_target_update = 0
        if cfg.input_ is None:
            self._env_keys = jax.random.split(
                self.next_key(), cfg.num_envs_per_worker)
            state, obs = jax.vmap(self.env.reset)(self._env_keys)
            self._carry = {"env_state": state, "obs": obs,
                           "ep_ret": jnp.zeros(cfg.num_envs_per_worker),
                           "ep_len": jnp.zeros(cfg.num_envs_per_worker,
                                               jnp.int32)}
            self._sample_fn = jax.jit(self._sample_impl)
        self._update_fn = jax.jit(self._td_update)
        self._ep_returns: list = []
        self._ep_lens: list = []

    # -- compiled sampling fragment ---------------------------------------

    def _sample_impl(self, params, carry, key, epsilon):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions, _, _ = self.module.compute_actions(
                params, obs, k_act, epsilon=epsilon)
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            ep_ret = carry["ep_ret"] + reward
            ep_len = carry["ep_len"] + 1
            out = {sb.OBS: obs, sb.ACTIONS: actions, sb.REWARDS: reward,
                   sb.NEXT_OBS: next_obs, sb.DONES: done,
                   "episode_return": jnp.where(done, ep_ret, jnp.nan),
                   "episode_len": jnp.where(done, ep_len, -1)}
            new_carry = {"env_state": state, "obs": next_obs,
                         "ep_ret": jnp.where(done, 0.0, ep_ret),
                         "ep_len": jnp.where(done, 0, ep_len)}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        carry, traj = jax.lax.scan(one_step, carry, keys)
        return carry, traj

    # NOTE: next_obs recorded on done is the auto-reset obs, but the done
    # mask zeroes the bootstrap term so the target is unaffected.

    def _td_update(self, params, target_params, opt_state, batch):
        cfg = self.algo_config

        def loss_fn(p):
            q = self.module.q_values(p, batch[sb.OBS])
            q_sel = jnp.take_along_axis(
                q, batch[sb.ACTIONS][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            q_next_target = self.module.q_values(
                target_params, batch[sb.NEXT_OBS])
            if cfg.double_q:
                q_next_online = self.module.q_values(p, batch[sb.NEXT_OBS])
                best = jnp.argmax(q_next_online, axis=-1)
            else:
                best = jnp.argmax(q_next_target, axis=-1)
            q_next = jnp.take_along_axis(
                q_next_target, best[..., None], axis=-1)[..., 0]
            nonterm = 1.0 - batch[sb.DONES].astype(jnp.float32)
            # n-step batches carry their own gamma^k * nonterminal column;
            # 1-step batches (external input, Ape-X shards) fall back to
            # the classic gamma * (1 - done) mask.
            disc = batch.get("discounts", cfg.gamma * nonterm)
            target = batch[sb.REWARDS] + disc * \
                jax.lax.stop_gradient(q_next)
            td_error = q_sel - target
            weights = batch.get("weights", jnp.ones_like(td_error))
            loss = jnp.mean(weights * optax.huber_loss(q_sel, target))
            return loss, td_error

        (loss, td_error), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, td_error

    # ---------------------------------------------------------------------

    def compute_single_action(self, obs, explore: bool = False,
                              epsilon: float | None = None):
        """Epsilon-greedy single action (QModule's knob is epsilon, not
        the base class's explore flag); jitted once — this is the hot
        call when serving external PolicyClients, which invoke it from
        one thread PER CONNECTION, so RNG splitting and lazy init are
        lock-guarded."""
        with self._act_lock:
            if not hasattr(self, "_act_fn"):
                self._act_fn = jax.jit(
                    lambda p, o, k, e: self.module.compute_actions(
                        p, o, k, epsilon=e)[0])
            key = self.next_key()
            params = self.params
        eps = epsilon if epsilon is not None else (
            self._epsilon() if explore else 0.0)
        a = self._act_fn(params, jnp.asarray(obs)[None], key,
                         jnp.asarray(eps))
        return int(np.asarray(a)[0])

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _ingest_external(self) -> None:
        """Pull one batch from the external input seam (policy server /
        offline reader / callable) into the replay buffer."""
        if not hasattr(self, "_input_src"):
            from ray_tpu.rllib.offline import resolve_input
            self._input_src = resolve_input(self.algo_config.input_)
        src = self._input_src
        batch = src() if callable(src) else src.next()
        flat = {k: np.asarray(v) for k, v in batch.items()}
        self.buffer.add_batch(flat)
        n = len(flat[sb.REWARDS])
        self._steps_sampled += n
        # Episode stats from done boundaries. External fragments may
        # start/end mid-episode (JsonReader shards), so the running
        # accumulators carry across batches instead of assuming each
        # batch is episode-aligned.
        dones = flat.get(sb.DONES)
        if dones is not None:
            if not hasattr(self, "_ext_ret"):
                self._ext_ret, self._ext_len = 0.0, 0
            rewards = np.asarray(flat[sb.REWARDS], np.float64)
            for r, d in zip(rewards, np.asarray(dones, bool)):
                self._ext_ret += float(r)
                self._ext_len += 1
                if d:
                    self._ep_returns.append(self._ext_ret)
                    self._ep_lens.append(self._ext_len)
                    self._ext_ret, self._ext_len = 0.0, 0
            self._ep_returns = self._ep_returns[-100:]
            self._ep_lens = self._ep_lens[-100:]

    def training_step(self) -> dict:
        cfg = self.algo_config
        losses = []
        if cfg.input_ is not None:
            self._ingest_external()
        else:
            # sample until one update's worth of new experience is in
            self._carry, traj = self._sample_fn(
                self.params, self._carry, self.next_key(),
                jnp.asarray(self._epsilon()))
            host = {k: np.asarray(v) for k, v in traj.items()}
            rets = host.pop("episode_return").ravel()
            lens = host.pop("episode_len").ravel()
            fin = ~np.isnan(rets)
            self._ep_returns.extend(rets[fin].tolist())
            self._ep_lens.extend(lens[fin & (lens >= 0)].tolist())
            self._ep_returns = self._ep_returns[-100:]
            self._ep_lens = self._ep_lens[-100:]
            if cfg.n_step > 1:
                host = _n_step_fragment(host, cfg.n_step, cfg.gamma)
            flat = {k: v.reshape((-1,) + v.shape[2:])
                    for k, v in host.items()}
            self.buffer.add_batch(flat)
            self._steps_sampled += len(flat[sb.REWARDS])

        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.n_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                device_batch = {k: jnp.asarray(v) for k, v in batch.items()
                                if k != "batch_indexes"}
                self.params, self.opt_state, loss, td = self._update_fn(
                    self.params, self.target_params, self.opt_state,
                    device_batch)
                losses.append(float(loss))
                self._num_updates += 1
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        batch["batch_indexes"], np.asarray(td))
                if (self._num_updates - self._last_target_update
                        >= cfg.target_network_update_freq):
                    self.target_params = jax.tree.map(jnp.copy, self.params)
                    self._last_target_update = self._num_updates

        return {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self._ep_lens))
                                 if self._ep_lens else float("nan")),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "num_env_steps_sampled": self._steps_sampled,
            "buffer_size": len(self.buffer),
        }

    def get_state(self) -> dict:
        return {"params": self.params, "target_params": self.target_params,
                "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]


register_algorithm("DQN", DQN)
