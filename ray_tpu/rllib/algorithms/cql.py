"""CQL — conservative Q-learning for offline continuous control.

Counterpart of the reference's `rllib/algorithms/cql/` (cql.py config on
top of SAC; loss `cql_torch_policy.py`: SAC's actor/critic/alpha losses
plus the CQL(H) regularizer — logsumexp over random + policy actions of Q
minus Q on dataset actions, weighted by `min_q_weight`). Trains purely
from offline shards (no environment interaction, no rollout state): the
replay buffer is sized to the dataset and filled once at setup. The SAC
loss itself is reused via `_sac_update(extra_loss=...)`, so SAC fixes
(e.g. no_done_at_end) apply here automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import register_algorithm
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig, _sample_squashed
from ray_tpu.rllib.env.jax_env import make_env
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.offline import resolve_input
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.input_ = None              # offline shards (required)
        self.min_q_weight = 5.0
        self.num_cql_actions = 4        # sampled actions for the logsumexp
        self.learning_starts = 0
        self.n_updates_per_iter = 64

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self


class CQL(SAC):
    _config_class = CQLConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if not cfg.input_:
            raise ValueError("CQL requires config.offline_data(input_=...)")
        # env used for spaces only — works with any registered env, no
        # JaxEnv requirement since CQL never rolls out
        self.env = make_env(cfg.env, cfg.env_config)
        if not isinstance(self.env.action_space, Box):
            raise ValueError("CQL requires a continuous (Box) action space")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.build_learner()
        # fill the buffer once from the offline shards; actions in the
        # dataset are env-scaled — map back to the actor's tanh range
        data = resolve_input(cfg.input_).read_all()
        n = len(data[sb.REWARDS])
        if n > cfg.buffer_size:
            # never silently truncate the dataset to the ring size
            self.buffer = ReplayBuffer(n, seed=cfg.seed)
        from ray_tpu.rllib.offline import actions_to_unit
        unit = actions_to_unit(data[sb.ACTIONS],
                               np.asarray(self._act_low),
                               np.asarray(self._act_high))
        self.buffer.add_batch({
            sb.OBS: np.asarray(data[sb.OBS], np.float32),
            sb.ACTIONS: unit,
            sb.REWARDS: np.asarray(data[sb.REWARDS], np.float32),
            sb.NEXT_OBS: np.asarray(data[sb.NEXT_OBS], np.float32),
            sb.DONES: np.asarray(data[sb.DONES]),
        })

    def build_learner(self) -> None:
        # learner half only: no env vmap/rollout carry (offline)
        self._build_networks()

    def _cql_penalty(self, p, batch, key):
        """CQL(H): E_s[logsumexp_a Q(s,a) - Q(s, a_data)] over random
        uniform + current-policy actions (cql_torch_policy.py). The policy
        actions are stop_gradient'ed: with the fused optimizer the
        penalty must shape only the Q-nets, not push the actor toward
        low-Q actions."""
        cfg = self.algo_config
        k_rand, k_pi = jax.random.split(key)
        B = batch[sb.REWARDS].shape[0]
        N = cfg.num_cql_actions
        obs_rep = jnp.repeat(batch[sb.OBS], N, axis=0)
        rand_act = jax.random.uniform(
            k_rand, (B * N, self._act_dim), minval=-1.0, maxval=1.0)
        mean, log_std = self.actor.apply(p["actor"], obs_rep)
        pi_act, pi_logp = _sample_squashed(mean, log_std, k_pi)
        pi_act = jax.lax.stop_gradient(pi_act)
        pi_logp = jax.lax.stop_gradient(pi_logp)
        penalty = 0.0
        for qnet, qp in ((self.q1, p["q1"]), (self.q2, p["q2"])):
            q_rand = qnet.apply(qp, obs_rep, rand_act).reshape(B, N)
            # importance correction: uniform density over [-1,1]^d
            q_rand = q_rand + self._act_dim * jnp.log(2.0)
            q_pi = qnet.apply(qp, obs_rep, pi_act).reshape(B, N) - \
                pi_logp.reshape(B, N)
            cat = jnp.concatenate([q_rand, q_pi], axis=1)
            lse = jax.scipy.special.logsumexp(cat, axis=1) - \
                jnp.log(2.0 * N)
            q_data = qnet.apply(qp, batch[sb.OBS], batch[sb.ACTIONS])
            penalty = penalty + jnp.mean(lse - q_data)
        return self.algo_config.min_q_weight * penalty

    def _one_update(self, params, target_q, opt_state, batch, key):
        return self._sac_update(params, target_q, opt_state, batch, key,
                                extra_loss=self._cql_penalty)

    def training_step(self) -> dict:
        cfg = self.algo_config
        batches = self._sample_update_batches(cfg.n_updates_per_iter)
        (self.params, self.target_q, self.opt_state, loss_v,
         alpha_v) = self._update_many_fn(
            self.params, self.target_q, self.opt_state, batches,
            self.next_key())
        return {"loss": float(np.mean(np.asarray(loss_v))),
                "alpha": float(np.mean(np.asarray(alpha_v))),
                "buffer_size": len(self.buffer)}


register_algorithm("CQL", CQL)
