"""AlphaZero — self-play MCTS + policy/value network (Silver et al.).

Counterpart of the reference's `rllib/algorithms/alpha_zero/`
(alpha_zero.py + `mcts.py` + `alpha_zero_policy.py`): PUCT tree search
over a perfect-information game, self-play targets (visit-count policy,
final outcome value), and a joint policy+value network trained on the
replayed games. Like the reference, the SEARCH runs host-side (its
mcts.py is a python tree too); the TPU-first part is batching — every
network evaluation during search and self-play batches across all
parallel games/leaves into one jitted call, and the train step is one
jitted program. (A fully in-graph mctx-style fixed-array search is the
natural next step on this substrate; the host tree keeps v1 honest.)

Ships with TicTacToe as the canonical two-player JaxEnv-style game
(board from the CURRENT player's perspective: +1 own, -1 opponent), the
same role CartPole plays for the single-agent algorithms.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)


# ---------------------------------------------------------------------------
# TicTacToe (canonical perspective: +1 = to-move player's stones)
# ---------------------------------------------------------------------------

_LINES = np.array(
    [[0, 1, 2], [3, 4, 5], [6, 7, 8],
     [0, 3, 6], [1, 4, 7], [2, 5, 8],
     [0, 4, 8], [2, 4, 6]])


class TicTacToe:
    """Perfect-information 2-player game API used by the search:
    initial() -> board; legal(board) -> mask; step(board, a) ->
    (next_board_from_OPPONENT_view, reward_for_mover, done)."""

    num_actions = 9
    obs_shape = (9,)

    def initial(self) -> np.ndarray:
        return np.zeros(9, np.float32)

    @staticmethod
    def legal(board: np.ndarray) -> np.ndarray:
        return (board == 0).astype(np.float32)

    @staticmethod
    def step(board: np.ndarray, action: int):
        b = board.copy()
        b[action] = 1.0
        won = bool((b[_LINES] == 1).all(axis=1).any())
        full = bool((b != 0).all())
        if won:
            return -b, 1.0, True          # mover wins
        if full:
            return -b, 0.0, True          # draw
        return -b, 0.0, False             # flip perspective for opponent


# ---------------------------------------------------------------------------
# network
# ---------------------------------------------------------------------------

class _AZNet(nn.Module):
    num_actions: int
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        h = x
        for _ in range(2):
            h = nn.relu(nn.Dense(self.hidden)(h))
        logits = nn.Dense(self.num_actions)(h)
        value = jnp.tanh(nn.Dense(1)(nn.relu(nn.Dense(self.hidden)(h))))
        return logits, value[..., 0]


# ---------------------------------------------------------------------------
# PUCT search (host tree, batched network evals)
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "board",
                 "terminal", "reward")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: dict[int, "_Node"] = {}
        self.board = None
        self.terminal = False
        self.reward = 0.0

    @property
    def q(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class MCTS:
    """PUCT search (reference: alpha_zero/mcts.py). `evaluate` is a
    BATCHED callable boards[B,obs] -> (priors[B,A], values[B]) so many
    concurrent searches share one device call per wave."""

    def __init__(self, game, evaluate, num_sims: int = 64,
                 c_puct: float = 1.5, dirichlet_alpha: float = 0.6,
                 noise_frac: float = 0.25, rng=None):
        self.game = game
        self.evaluate = evaluate
        self.num_sims = num_sims
        self.c_puct = c_puct
        self.dirichlet_alpha = dirichlet_alpha
        self.noise_frac = noise_frac
        self.rng = rng or np.random.default_rng(0)

    def _select_child(self, node: _Node):
        total = max(1, node.visits)
        best, best_score = None, -np.inf
        for a, child in node.children.items():
            # child.q is from the OPPONENT's view after the move: negate
            u = (-child.q + self.c_puct * child.prior
                 * np.sqrt(total) / (1 + child.visits))
            if u > best_score:
                best, best_score = a, u
        return best, node.children[best]

    def _expand(self, node: _Node, priors: np.ndarray):
        mask = self.game.legal(node.board)
        p = priors * mask
        p = p / p.sum() if p.sum() > 0 else mask / mask.sum()
        for a in np.nonzero(mask)[0]:
            child = _Node(float(p[a]))
            nxt, reward, done = self.game.step(node.board, int(a))
            child.board = nxt
            child.terminal = done
            child.reward = reward
            node.children[int(a)] = child

    def run_batch(self, boards: list[np.ndarray], add_noise: bool = True):
        """Search every board; -> (visit_policies [B,A], root_values [B]).
        All network evaluations across the batch happen in ONE device
        call per simulation wave."""
        roots = []
        out = self.evaluate(np.stack(boards))
        priors0, _vals0 = np.asarray(out[0]), np.asarray(out[1])
        for b, board in enumerate(boards):
            root = _Node(1.0)
            root.board = board
            pri = priors0[b]
            if add_noise:
                mask = self.game.legal(board)
                noise = np.zeros_like(pri)
                idx = np.nonzero(mask)[0]
                noise[idx] = self.rng.dirichlet(
                    [self.dirichlet_alpha] * len(idx))
                pri = (1 - self.noise_frac) * pri + self.noise_frac * noise
            self._expand(root, pri)
            roots.append(root)

        for _ in range(self.num_sims):
            paths, leaves, eval_idx = [], [], []
            for root in roots:
                node, path = root, []
                while node.children:
                    a, node = self._select_child(node)
                    path.append(node)
                paths.append(path)
                leaves.append(node)
                if not node.terminal:
                    eval_idx.append(len(leaves) - 1)
            if eval_idx:
                boards_b = np.stack([leaves[i].board for i in eval_idx])
                pri_b, val_b = self.evaluate(boards_b)
                pri_b, val_b = np.asarray(pri_b), np.asarray(val_b)
            k = 0
            for i, (leaf, path) in enumerate(zip(leaves, paths)):
                if leaf.terminal:
                    # terminal value is the REWARD to the player who
                    # moved INTO the leaf, from the leaf mover's view
                    value = -leaf.reward
                else:
                    self._expand(leaf, pri_b[k])
                    value = float(val_b[k])
                    k += 1
                # backup: value alternates sign up the path
                node_value = value
                for node in reversed(path):
                    node.visits += 1
                    node.value_sum += node_value
                    node_value = -node_value
                roots[i].visits += 1
                roots[i].value_sum += node_value
        pis, vals = [], []
        for root in roots:
            pi = np.zeros(self.game.num_actions, np.float32)
            for a, child in root.children.items():
                pi[a] = child.visits
            pi = pi / pi.sum() if pi.sum() else pi
            pis.append(pi)
            vals.append(root.q)
        return np.stack(pis), np.asarray(vals)


# ---------------------------------------------------------------------------
# algorithm
# ---------------------------------------------------------------------------

class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaZero)
        self.lr = 3e-3
        self.num_sims = 48
        self.c_puct = 1.5
        self.games_per_iter = 24          # self-play games per iteration
        self.parallel_games = 24          # searched as one eval batch
        self.train_batch_size = 128
        self.n_updates_per_iter = 20
        self.buffer_size = 20_000
        self.temperature_moves = 4        # sample pi before, argmax after
        self.hidden = 128
        self.game = TicTacToe             # class or instance


class AlphaZero(Algorithm):
    _config_class = AlphaZeroConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.game = cfg.game() if isinstance(cfg.game, type) else cfg.game
        self.net = _AZNet(self.game.num_actions, cfg.hidden)
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.params = self.net.init(
            self.next_key(), jnp.zeros((1, *self.game.obs_shape)))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._apply = jax.jit(self.net.apply)
        self._update_fn = jax.jit(self._update)
        # replay of (board, pi, z)
        self._obs: list = []
        self._pi: list = []
        self._z: list = []
        self._games_played = 0

    # -- network seam for the search --------------------------------------

    def _evaluate(self, boards: np.ndarray):
        logits, values = self._apply(self.params, jnp.asarray(boards))
        return np.asarray(jax.nn.softmax(logits)), np.asarray(values)

    # -- self-play ---------------------------------------------------------

    def _self_play(self, n_games: int):
        cfg = self.algo_config
        mcts = MCTS(self.game, self._evaluate, cfg.num_sims, cfg.c_puct,
                    rng=self._np_rng)
        boards = [self.game.initial() for _ in range(n_games)]
        # per-game trajectories: (board, pi, mover_sign)
        traj: list[list] = [[] for _ in range(n_games)]
        outcome = [None] * n_games      # +1 mover-at-end won, 0 draw
        move_no = 0
        live = list(range(n_games))
        while live:
            live_boards = [boards[g] for g in live]
            pis, _ = mcts.run_batch(live_boards)
            next_live = []
            for j, g in enumerate(live):
                pi = pis[j]
                if move_no < cfg.temperature_moves:
                    a = int(self._np_rng.choice(len(pi), p=pi))
                else:
                    a = int(np.argmax(pi))
                traj[g].append((boards[g].copy(), pi))
                nxt, reward, done = self.game.step(boards[g], a)
                boards[g] = nxt
                if done:
                    outcome[g] = reward     # reward to the LAST mover
                else:
                    next_live.append(g)
            live = next_live
            move_no += 1
        # value targets: z from each position's MOVER perspective —
        # the last mover got `outcome`; alternate backwards
        for g in range(n_games):
            z = outcome[g]
            for board, pi in reversed(traj[g]):
                self._obs.append(board)
                self._pi.append(pi)
                self._z.append(z)
                z = -z
        cap = self.algo_config.buffer_size
        self._obs = self._obs[-cap:]
        self._pi = self._pi[-cap:]
        self._z = self._z[-cap:]
        self._games_played += n_games
        return [o for o in outcome]

    # -- training ----------------------------------------------------------

    def _update(self, params, opt_state, obs, pi, z):
        def loss_fn(p):
            logits, value = self.net.apply(p, obs)
            policy_loss = -jnp.mean(
                jnp.sum(pi * jax.nn.log_softmax(logits), axis=-1))
            value_loss = jnp.mean((value - z) ** 2)
            return policy_loss + value_loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return optax.apply_updates(params, updates), opt_state, loss

    def training_step(self) -> dict:
        cfg = self.algo_config
        outcomes = self._self_play(cfg.games_per_iter)
        losses = []
        n = len(self._obs)
        for _ in range(cfg.n_updates_per_iter):
            idx = self._np_rng.integers(0, n, min(cfg.train_batch_size, n))
            obs = jnp.asarray(np.stack([self._obs[i] for i in idx]))
            pi = jnp.asarray(np.stack([self._pi[i] for i in idx]))
            z = jnp.asarray(np.asarray([self._z[i] for i in idx],
                                       np.float32))
            self.params, self.opt_state, loss = self._update_fn(
                self.params, self.opt_state, obs, pi, z)
            losses.append(float(loss))
        wins = sum(1 for o in outcomes if o > 0)
        draws = sum(1 for o in outcomes if o == 0)
        return {
            "loss": float(np.mean(losses)),
            "games_played": self._games_played,
            "selfplay_decisive_frac": wins / max(1, len(outcomes)),
            "selfplay_draw_frac": draws / max(1, len(outcomes)),
            "replay_positions": len(self._obs),
            "episode_reward_mean": float("nan"),   # 2-player: n/a
        }

    # -- acting ------------------------------------------------------------

    def compute_single_action(self, board, num_sims: int | None = None):
        """Best move for `board` (current-player perspective) by search."""
        cfg = self.algo_config
        mcts = MCTS(self.game, self._evaluate,
                    num_sims or cfg.num_sims, cfg.c_puct,
                    rng=self._np_rng)
        pi, _ = mcts.run_batch([np.asarray(board, np.float32)],
                               add_noise=False)
        return int(np.argmax(pi[0]))

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("AlphaZero", AlphaZero)
