"""Multi-agent PPO: per-policy modules + policy mapping over a fixed
agent set.

Counterpart of the reference's multi-agent stack
(`rllib/env/multi_agent_env.py` + `policy/policy_map.py` + the
policies/policy_mapping_fn config surface of algorithm_config.py). The
TPU-native shape keeps everything in one compiled program: the policy
mapping is resolved at TRACE time (the agent set is fixed), so the
rollout scan applies each agent's policy network inline, GAE runs per
agent, and the per-policy SGD loops over concatenated agent batches —
one XLA program per iteration, no per-agent Python dispatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, register_algorithm)
from ray_tpu.rllib.algorithms.ppo import PPOConfig, _gae_scan, _ppo_loss
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.jax_env import make_env
from ray_tpu.rllib.env.multi_agent import is_multi_agent_env


class MAPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MultiAgentPPO)
        self.policies: dict = {}           # pid -> None (spaces from env)
        self.policy_mapping_fn = None      # (agent_id) -> pid

    def multi_agent(self, *, policies=None, policy_mapping_fn=None):
        """Reference: AlgorithmConfig.multi_agent(policies=...,
        policy_mapping_fn=...)."""
        if policies is not None:
            self.policies = (dict.fromkeys(policies)
                             if not isinstance(policies, dict)
                             else dict(policies))
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    _config_class = MAPPOConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        self.env = make_env(cfg.env, cfg.env_config)
        if not is_multi_agent_env(self.env):
            raise ValueError("MultiAgentPPO requires a MultiAgentJaxEnv")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.agent_ids = tuple(self.env.agent_ids)
        if not cfg.policies:
            cfg.policies = {"default_policy": None}
        mapping = cfg.policy_mapping_fn or (
            lambda aid: next(iter(cfg.policies)))
        # resolved ONCE — the mapping is static for the compiled program
        self._agent_policy = {aid: mapping(aid) for aid in self.agent_ids}
        unknown = set(self._agent_policy.values()) - set(cfg.policies)
        if unknown:
            raise ValueError(
                f"policy_mapping_fn returned unknown policies {unknown}")
        self.modules = {}
        self.params = {}
        for pid in cfg.policies:
            # spaces come from any agent mapped to this policy
            aid = next(
                (a for a, p in self._agent_policy.items() if p == pid),
                None)
            if aid is None:
                raise ValueError(
                    f"policy {pid!r} has no agent mapped to it "
                    f"(mapping: {self._agent_policy}); drop it from "
                    "`policies` or fix policy_mapping_fn")
            mod = RLModule(self.env.observation_space(aid),
                           self.env.action_space(aid), dict(cfg.model))
            self.modules[pid] = mod
            self.params[pid] = mod.init(self.next_key())
        chain = []
        if cfg.grad_clip:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.optimizer = optax.chain(*chain)
        # one optimizer STATE per policy: a shared Adam state over the
        # whole dict would keep moving policy B from its stale momentum
        # while policy A trains (zero grad != no Adam update)
        self.opt_state = {pid: self.optimizer.init(self.params[pid])
                          for pid in cfg.policies}
        keys = jax.random.split(self.next_key(), cfg.num_envs_per_worker)
        state, obs = jax.vmap(self.env.reset)(keys)
        self._carry = {"env_state": state, "obs": obs,
                       "ep_ret": {aid: jnp.zeros(cfg.num_envs_per_worker)
                                  for aid in self.agent_ids}}
        self._train_fn = jax.jit(self._fused_iteration)
        self._ep_returns: list = []

    # -- compiled rollout + per-policy SGD ---------------------------------

    def _unroll(self, params, carry, key):
        cfg = self.algo_config

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions, logps, values = {}, {}, {}
            akeys = jax.random.split(k_act, len(self.agent_ids))
            for i, aid in enumerate(self.agent_ids):
                pid = self._agent_policy[aid]
                dist, value = self.modules[pid].forward(params[pid],
                                                        obs[aid])
                act = dist.sample(akeys[i])
                actions[aid] = act
                logps[aid] = dist.logp(act)
                values[aid] = value
            env_keys = jax.random.split(k_env, cfg.num_envs_per_worker)
            state, next_obs, rewards, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            ep_ret = {aid: carry["ep_ret"][aid] + rewards[aid]
                      for aid in self.agent_ids}
            out = {
                "obs": obs, "actions": actions, "logps": logps,
                "values": values, "rewards": rewards, "done": done,
                "episode_return": {
                    aid: jnp.where(done, ep_ret[aid], jnp.nan)
                    for aid in self.agent_ids},
            }
            new_carry = {
                "env_state": state, "obs": next_obs,
                "ep_ret": {aid: jnp.where(done, 0.0, ep_ret[aid])
                           for aid in self.agent_ids}}
            return new_carry, out

        keys = jax.random.split(key, cfg.rollout_fragment_length)
        carry, traj = jax.lax.scan(one_step, carry, keys)
        # bootstrap values at the final obs, per agent
        last_values = {}
        for aid in self.agent_ids:
            pid = self._agent_policy[aid]
            _, v = self.modules[pid].forward(params[pid],
                                             carry["obs"][aid])
            last_values[aid] = v
        return carry, traj, last_values

    def _fused_iteration(self, params, opt_state, carry, key):
        cfg = self.algo_config
        k_sample, k_sgd = jax.random.split(key)
        carry, traj, last_values = self._unroll(params, carry, k_sample)
        # per-agent GAE, then group flattened batches by policy
        per_policy: dict[str, list] = {pid: [] for pid in cfg.policies}
        for aid in self.agent_ids:
            pid = self._agent_policy[aid]
            advs = _gae_scan(traj["rewards"][aid], traj["values"][aid],
                             traj["done"], last_values[aid],
                             cfg.gamma, cfg.lambda_)
            targets = advs + traj["values"][aid]
            flat = {
                sb.OBS: traj["obs"][aid].reshape(
                    (-1,) + traj["obs"][aid].shape[2:]),
                sb.ACTIONS: traj["actions"][aid].reshape(
                    (-1,) + traj["actions"][aid].shape[2:]),
                sb.ACTION_LOGP: traj["logps"][aid].reshape(-1),
                sb.ADVANTAGES: advs.reshape(-1),
                sb.VALUE_TARGETS: targets.reshape(-1),
            }
            per_policy[pid].append(flat)
        stats_by_policy = {}
        params = dict(params)
        opt_state = dict(opt_state)
        for pid, parts in per_policy.items():
            if not parts:
                continue
            batch = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts)
            params[pid], opt_state[pid], stats = self._sgd_policy(
                pid, params[pid], opt_state[pid], batch, k_sgd)
            stats_by_policy[pid] = stats
        ep = {aid: traj["episode_return"][aid] for aid in self.agent_ids}
        return params, opt_state, carry, stats_by_policy, ep

    def _sgd_policy(self, pid, params, opt_state, flat, key):
        """Minibatch SGD on ONE policy's params with its OWN optimizer
        state — other policies are structurally untouched."""
        cfg = self.algo_config
        n = flat[sb.ADVANTAGES].shape[0]
        mb = min(cfg.sgd_minibatch_size, n)
        num_mb = max(n // mb, 1)
        adv = flat[sb.ADVANTAGES]
        flat = dict(flat)
        flat[sb.ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)

        loss_fn = functools.partial(
            _ppo_loss, self.modules[pid],
            clip_param=cfg.clip_param, vf_clip_param=cfg.vf_clip_param,
            vf_loss_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff)

        def one_minibatch(state, batch):
            params, opt_state = state
            (_, stats), grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch), has_aux=True)(params)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), stats

        def one_epoch(state, epoch_key):
            perm = jax.random.permutation(epoch_key, n)
            shuffled = jax.tree.map(
                lambda v: v[perm][:num_mb * mb].reshape(
                    (num_mb, mb) + v.shape[1:]), flat)
            state, stats = jax.lax.scan(one_minibatch, state, shuffled)
            return state, jax.tree.map(jnp.mean, stats)

        epoch_keys = jax.random.split(key, cfg.num_sgd_iter)
        (params, opt_state), stats = jax.lax.scan(
            one_epoch, (params, opt_state), epoch_keys)
        return params, opt_state, jax.tree.map(jnp.mean, stats)

    # ----------------------------------------------------------------------

    def training_step(self) -> dict:
        self.params, self.opt_state, self._carry, stats, ep = \
            self._train_fn(self.params, self.opt_state, self._carry,
                           self.next_key())
        # mean finished-episode return per agent, then summed over agents
        # (the reference reports episode_reward_mean as the episode's
        # TOTAL reward across agents)
        totals = []
        for aid in self.agent_ids:
            rets = np.asarray(ep[aid]).ravel()
            rets = rets[~np.isnan(rets)]
            if rets.size:
                totals.append(rets.mean())
        if totals:
            self._ep_returns.append(float(np.sum(totals)))
            self._ep_returns = self._ep_returns[-50:]
        metrics = {
            "episode_reward_mean": (float(np.mean(self._ep_returns))
                                    if self._ep_returns else float("nan")),
        }
        for pid, s in stats.items():
            for k, v in s.items():
                metrics[f"{pid}/{k}"] = float(np.asarray(v))
        return metrics

    def compute_actions(self, obs_dict: dict, explore: bool = False):
        """Per-agent greedy/sampled actions for serving/eval."""
        out = {}
        for aid, obs in obs_dict.items():
            pid = self._agent_policy[aid]
            dist, _ = self.modules[pid].forward(
                self.params[pid], jnp.asarray(obs)[None])
            act = (dist.sample(self.next_key()) if explore
                   else dist.deterministic())
            out[aid] = np.asarray(act)[0]
        return out

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


register_algorithm("MultiAgentPPO", MultiAgentPPO)
