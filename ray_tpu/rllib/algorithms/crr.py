"""CRR — critic-regularized regression (Wang et al. 2020).

Counterpart of the reference's `rllib/algorithms/crr/crr.py`: OFFLINE
continuous control by advantage-weighted behaviour cloning. The actor
never maximizes Q directly (the failure mode of offline DDPG — exploiting
critic errors on out-of-distribution actions); instead it regresses
toward DATASET actions weighted by the critic's advantage:

    L_actor = -E[ log pi(a|s) * f(A(s,a)) ]
    f = 1[A > 0]            ("binary" mode)
      | exp(A / beta) clipped ("exp" mode)
    A(s,a) = Q(s,a) - (1/m) sum_j Q(s, a_j),  a_j ~ pi(.|s)

The critic is a twin-Q TD learner on dataset transitions with target
networks (no CQL penalty needed — the actor is already constrained to
the data). One jitted update does critic + actor + polyak targets.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.algorithm import (
    Algorithm, AlgorithmConfig, register_algorithm)
from ray_tpu.rllib.env.spaces import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class _GaussianActor(nn.Module):
    act_dim: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.tanh(nn.Dense(self.act_dim)(x))
        log_std = self.param("log_std", nn.initializers.constant(-0.5),
                             (self.act_dim,))
        return mean, jnp.broadcast_to(log_std, mean.shape)


class _QNet(nn.Module):
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(1)(x)[..., 0]


class CRRConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CRR)
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.weight_mode = "exp"         # "exp" | "binary"
        self.beta = 1.0                  # exp temperature
        self.weight_clip = 20.0
        self.n_action_samples = 4        # m in the advantage baseline
        self.train_batch_size = 256
        self.n_updates_per_iter = 64
        self.input_ = None               # offline data (required)
        self.buffer_size = 1_000_000
        self.actor_hiddens = (64, 64)
        self.critic_hiddens = (64, 64)

    def offline_data(self, *, input_=None):
        if input_ is not None:
            self.input_ = input_
        return self


class CRR(Algorithm):
    _config_class = CRRConfig

    def setup(self, config: dict) -> None:
        cfg = self.algo_config
        if not cfg.input_:
            raise ValueError("CRR is an OFFLINE algorithm: pass data via "
                             "config.offline_data(input_=...)")
        from ray_tpu.rllib.env.jax_env import make_env
        self.env = make_env(cfg.env, cfg.env_config)
        if not isinstance(self.env.action_space, Box):
            raise ValueError("CRR requires a continuous (Box) action "
                             "space")
        self._rng = jax.random.PRNGKey(cfg.seed)
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.act_dim = int(np.prod(self.env.action_space.shape))
        self._act_low = np.asarray(self.env.action_space.low,
                                   np.float32).reshape(self.act_dim)
        self._act_high = np.asarray(self.env.action_space.high,
                                    np.float32).reshape(self.act_dim)
        self.actor = _GaussianActor(self.act_dim,
                                    tuple(cfg.actor_hiddens))
        self.q1 = _QNet(tuple(cfg.critic_hiddens))
        self.q2 = _QNet(tuple(cfg.critic_hiddens))
        dummy_o = jnp.zeros((1, self.obs_dim))
        dummy_a = jnp.zeros((1, self.act_dim))
        self.params = {
            "actor": self.actor.init(self.next_key(), dummy_o)["params"],
            "q1": self.q1.init(self.next_key(), dummy_o,
                               dummy_a)["params"],
            "q2": self.q2.init(self.next_key(), dummy_o,
                               dummy_a)["params"],
        }
        self.build_learner()

    def build_learner(self) -> None:
        cfg = self.algo_config
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        from ray_tpu.rllib.offline import resolve_input
        data = resolve_input(cfg.input_).read_all()
        n = len(data[sb.REWARDS])
        self.buffer = ReplayBuffer(max(n, cfg.buffer_size),
                                   seed=cfg.seed)
        from ray_tpu.rllib.offline import actions_to_unit
        unit = actions_to_unit(
            np.asarray(data[sb.ACTIONS]).reshape(n, self.act_dim),
            self._act_low, self._act_high)
        self.buffer.add_batch({
            sb.OBS: np.asarray(data[sb.OBS], np.float32).reshape(
                n, self.obs_dim),
            sb.ACTIONS: unit,
            sb.REWARDS: np.asarray(data[sb.REWARDS], np.float32),
            sb.DONES: np.asarray(data[sb.DONES]),
            sb.NEXT_OBS: np.asarray(data[sb.NEXT_OBS],
                                    np.float32).reshape(n, self.obs_dim),
        })
        self._update_fn = jax.jit(self._crr_update)
        self._num_updates = 0

    # -- jitted update -----------------------------------------------------

    def _logp(self, mean, log_std, act):
        var = jnp.exp(2 * log_std)
        return jnp.sum(
            -0.5 * jnp.square(act - mean) / var - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    def _crr_update(self, params, target_params, opt_state, batch, key):
        cfg = self.algo_config
        obs, act = batch[sb.OBS], batch[sb.ACTIONS]
        nonterm = 1.0 - batch[sb.DONES].astype(jnp.float32)
        k_next, k_base = jax.random.split(key)

        # TD target from target actor + min of twin target critics
        mean_n, log_std_n = self.actor.apply(
            {"params": target_params["actor"]}, batch[sb.NEXT_OBS])
        a_next = jnp.clip(
            mean_n + jnp.exp(log_std_n) * jax.random.normal(
                k_next, mean_n.shape), -1.0, 1.0)
        q_next = jnp.minimum(
            self.q1.apply({"params": target_params["q1"]},
                          batch[sb.NEXT_OBS], a_next),
            self.q2.apply({"params": target_params["q2"]},
                          batch[sb.NEXT_OBS], a_next))
        y = batch[sb.REWARDS] + cfg.gamma * nonterm * \
            jax.lax.stop_gradient(q_next)

        def loss_fn(p):
            q1 = self.q1.apply({"params": p["q1"]}, obs, act)
            q2 = self.q2.apply({"params": p["q2"]}, obs, act)
            critic_loss = jnp.mean(jnp.square(q1 - y)) + \
                jnp.mean(jnp.square(q2 - y))

            mean, log_std = self.actor.apply({"params": p["actor"]}, obs)
            # advantage vs the policy's own action distribution, under
            # the CURRENT (stop-grad) critic
            m = cfg.n_action_samples
            ks = jax.random.split(k_base, m)
            q_pi = []
            for i in range(m):
                a_i = jnp.clip(
                    jax.lax.stop_gradient(mean)
                    + jnp.exp(jax.lax.stop_gradient(log_std))
                    * jax.random.normal(ks[i], mean.shape), -1.0, 1.0)
                q_pi.append(self.q1.apply(
                    {"params": jax.lax.stop_gradient(p["q1"])}, obs, a_i))
            v_base = jnp.mean(jnp.stack(q_pi), axis=0)
            adv = jax.lax.stop_gradient(
                self.q1.apply({"params": jax.lax.stop_gradient(p["q1"])},
                              obs, act) - v_base)
            if cfg.weight_mode == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.minimum(jnp.exp(adv / cfg.beta), cfg.weight_clip)
            logp = self._logp(mean, log_std, act)
            actor_loss = -jnp.mean(w * logp)
            return critic_loss + actor_loss, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "advantage_mean": jnp.mean(adv), "weight_mean": jnp.mean(w)}

        (loss, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        target_params = jax.tree.map(
            lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
            target_params, params)
        stats["loss"] = loss
        return params, target_params, opt_state, stats

    def training_step(self) -> dict:
        cfg = self.algo_config
        stats = {}
        for _ in range(cfg.n_updates_per_iter):
            batch = {k: jnp.asarray(v) for k, v in
                     self.buffer.sample(cfg.train_batch_size).items()}
            (self.params, self.target_params, self.opt_state,
             stats) = self._update_fn(
                self.params, self.target_params, self.opt_state, batch,
                self.next_key())
            self._num_updates += 1
        return {"num_updates": self._num_updates,
                "episode_reward_mean": float("nan"),
                **{k: float(np.asarray(v)) for k, v in stats.items()}}

    def compute_single_action(self, obs, explore: bool = False):
        mean, log_std = self.actor.apply(
            {"params": self.params["actor"]},
            jnp.asarray(obs, jnp.float32).reshape(1, self.obs_dim))
        a = mean[0]
        if explore:
            a = a + jnp.exp(log_std[0]) * jax.random.normal(
                self.next_key(), a.shape)
        unit = np.asarray(jnp.clip(a, -1.0, 1.0))
        return (self._act_low
                + (unit + 1.0) * 0.5 * (self._act_high - self._act_low))

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]


register_algorithm("CRR", CRR)
