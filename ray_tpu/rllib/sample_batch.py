"""SampleBatch — columnar container for trajectory data.

Counterpart of the reference's `rllib/policy/sample_batch.py:98`
(SampleBatch) and `:1465` (MultiAgentBatch): a dict of equally-sized
arrays with the standard column names, plus concat/shuffle/minibatch
helpers. Arrays are host numpy (device transfer happens at the learner
boundary via device_put, keeping object-store transit zero-copy).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"


class SampleBatch(dict):
    """dict[str, np.ndarray] with batch semantics."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def __len__(self) -> int:        # len(batch) == rows, like the reference
        return self.count

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator | None = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int,
                    rng: np.random.Generator | None = None
                    ) -> Iterator["SampleBatch"]:
        batch = self.shuffle(rng) if rng is not None else self
        for i in range(0, batch.count - size + 1, size):
            yield batch.slice(i, i + size)

    def split_by_episode(self) -> List["SampleBatch"]:
        """Split on EPS_ID boundaries; without EPS_ID, fall back to DONES
        (each done row ends an episode); with neither, the whole batch is
        one episode."""
        if EPS_ID in self:
            ids = self[EPS_ID]
            boundaries = [0] + list(np.where(ids[1:] != ids[:-1])[0] + 1) \
                + [len(ids)]
        elif DONES in self:
            dones = np.asarray(self[DONES]).astype(bool)
            boundaries = [0] + list(np.flatnonzero(dones[:-1]) + 1) \
                + [len(dones)]
        else:
            return [self]
        return [self.slice(a, b)
                for a, b in zip(boundaries[:-1], boundaries[1:])]

    def __repr__(self):
        cols = {k: tuple(v.shape) for k, v in self.items()}
        return f"SampleBatch({self.count}: {cols})"


def concat_samples(batches: List[SampleBatch]) -> SampleBatch:
    """Reference: `SampleBatch.concat_samples` (sample_batch.py)."""
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({
        k: np.concatenate([b[k] for b in batches], axis=0) for k in keys})


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_value: float | np.ndarray, gamma: float,
                lam: float) -> Dict[str, np.ndarray]:
    """Generalized advantage estimation over a (possibly multi-episode)
    rollout (reference: `rllib/evaluation/postprocessing.py`
    compute_gae_for_sample_batch). Host-numpy reverse scan; the in-graph
    PPO path has a lax.scan twin in algorithms/ppo.py.
    """
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    lastgaelam = 0.0
    for t in reversed(range(T)):
        nonterminal = 1.0 - float(dones[t])
        next_v = last_value if t == T - 1 else values[t + 1]
        delta = rewards[t] + gamma * next_v * nonterminal - values[t]
        lastgaelam = delta + gamma * lam * nonterminal * lastgaelam
        adv[t] = lastgaelam
    return {ADVANTAGES: adv,
            VALUE_TARGETS: (adv + values).astype(np.float32)}
