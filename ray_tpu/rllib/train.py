"""`rllib train` CLI + tuned-example regression runner.

Counterpart of the reference's `rllib/train.py` / `rllib/scripts.py`
(`rllib train -f tuned_examples/ppo/cartpole-ppo.yaml`) and
`rllib/tests/run_regression_tests.py`: tuned YAMLs carry reward-threshold
stop criteria and double as learning regressions — the CI oracle for "the
algorithm still learns" (SURVEY.md §4.2).

Usage:
    python -m ray_tpu.rllib.train --algo PPO --env CartPole-v1 \
        --stop-reward 450 --stop-iters 60
    python -m ray_tpu.rllib.train -f tuned_examples/cartpole-ppo.yaml
"""

from __future__ import annotations

import argparse
import json
import os
import time

TUNED_EXAMPLES_DIR = os.path.join(os.path.dirname(__file__),
                                  "tuned_examples")


def run_experiment(algo_name: str, env: str, config: dict | None = None,
                   stop: dict | None = None, verbose: bool = True) -> dict:
    """Build an algorithm and train until a stop criterion hits.
    Returns {"passed", "best_reward", "iterations", "time_s"} — passed is
    True iff the reward threshold (when given) was reached."""
    from ray_tpu.rllib.algorithms import get_algorithm_class

    cls = get_algorithm_class(algo_name)
    cfg = cls.get_default_config()
    cfg.env = env
    cfg.update_from_dict(dict(config or {}))
    algo = cfg.build()
    stop = dict(stop or {})
    reward_target = stop.get("episode_reward_mean")
    max_iters = int(stop.get("training_iteration", 100))
    # wall-clock budget (reference: the tuned-example oracles are
    # time-to-result floors, e.g. pong-impala-fast.yaml) — the run FAILS
    # if the reward target isn't reached inside it
    time_budget = stop.get("time_total_s")
    best = float("-inf")
    t0 = time.time()
    i = 0
    try:
        for i in range(1, max_iters + 1):
            result = algo.train()
            rew = result.get("episode_reward_mean", float("nan"))
            if rew == rew:
                best = max(best, rew)
            if verbose and (i % 5 == 0 or i == 1):
                print(f"iter {i:4d} reward_mean="
                      f"{rew if rew == rew else float('nan'):9.2f} "
                      f"best={best:9.2f}")
            if reward_target is not None and best >= reward_target:
                break
            if time_budget is not None and time.time() - t0 >= time_budget:
                break
    finally:
        algo.cleanup()
    return {
        "passed": reward_target is None or best >= reward_target,
        "best_reward": best,
        "iterations": i,
        "time_s": time.time() - t0,
        "algo": algo_name,
        "env": env,
    }


def run_tuned_example(path: str, verbose: bool = True) -> dict:
    """Run one tuned-example YAML (reference format: {name: {run, env,
    stop, config}}) and return the run_experiment result."""
    import yaml

    if not os.path.exists(path):
        # resolve bare names / relative paths against the shipped dir so
        # `-f tuned_examples/cartpole-ppo.yaml` and `-f cartpole-ppo.yaml`
        # work from anywhere
        fallback = os.path.join(TUNED_EXAMPLES_DIR, os.path.basename(path))
        if os.path.exists(fallback):
            path = fallback
    with open(path) as f:
        spec = yaml.safe_load(f)
    name, body = next(iter(spec.items()))
    if verbose:
        print(f"== tuned example {name} ({body['run']} on {body['env']})")
    out = run_experiment(body["run"], body["env"],
                         config=body.get("config"),
                         stop=body.get("stop"), verbose=verbose)
    out["name"] = name
    return out


def list_tuned_examples() -> list:
    return sorted(
        os.path.join(TUNED_EXAMPLES_DIR, f)
        for f in os.listdir(TUNED_EXAMPLES_DIR)
        if f.endswith((".yaml", ".yml")))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="rllib train",
        description="Train an RL algorithm (reference: rllib train CLI)")
    parser.add_argument("-f", "--file", help="tuned-example YAML")
    parser.add_argument("--algo", "--run", dest="algo", help="algorithm id")
    parser.add_argument("--env", help="registered env id")
    parser.add_argument("--stop-reward", type=float, default=None)
    parser.add_argument("--stop-iters", type=int, default=100)
    parser.add_argument("--config", default="{}",
                        help="JSON dict of config overrides")
    args = parser.parse_args(argv)

    if args.file:
        result = run_tuned_example(args.file)
    else:
        if not args.algo or not args.env:
            parser.error("--algo and --env are required without -f")
        stop = {"training_iteration": args.stop_iters}
        if args.stop_reward is not None:
            stop["episode_reward_mean"] = args.stop_reward
        result = run_experiment(args.algo, args.env,
                                config=json.loads(args.config), stop=stop)
    print(json.dumps(result))
    return 0 if result["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
