"""ray_tpu.rllib — reinforcement learning library.

Counterpart of the reference's `python/ray/rllib/` (SURVEY.md §2.9), built
TPU-first rather than ported:

- **In-graph rollouts**: environments written as pure JAX step functions
  are vmapped over an env batch and unrolled with `lax.scan` INSIDE the
  jitted train step — sampling rides the accelerator instead of a fleet of
  CPU actors (the reference's `RolloutWorker.sample`,
  `rllib/evaluation/rollout_worker.py:660`, is a Python env loop).
- **Actor rollouts** remain available for arbitrary Python envs
  (`rllib/evaluation/` parity): a WorkerSet of `@remote` actors builds
  SampleBatches that return through the object store.
- **Learner = SPMD**: gradient sync is a `psum` over the mesh's data axis,
  not DDP (`rllib/core/learner/torch/torch_learner.py:261`).

Algorithms are `tune.Trainable`s, so `tune.run(PPO, config=...)` works the
way `Algorithm(Trainable)` does in the reference
(`rllib/algorithms/algorithm.py:191`).
"""

from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples
from ray_tpu.rllib.algorithms import (
    A2C, A2CConfig, APPO, APPOConfig, Algorithm, AlgorithmConfig, BC,
    BCConfig, CQL, CQLConfig, DDPG, DDPGConfig, DQN, DQNConfig, IMPALA,
    IMPALAConfig, MAPPOConfig, MARWIL, MARWILConfig, MultiAgentPPO, PPO,
    PPOConfig, SAC, SACConfig, TD3, TD3Config, ES, ESConfig,
    ApexDQN, ApexDQNConfig,
    LinTS, LinTSConfig, LinUCB, LinUCBConfig, get_algorithm_class,
    register_algorithm)
from ray_tpu.rllib.env.jax_env import make_env, register_env
from ray_tpu.rllib.env.multi_agent import CoopMatch, MultiAgentJaxEnv

__all__ = [
    "SampleBatch", "concat_samples",
    "Algorithm", "AlgorithmConfig", "get_algorithm_class",
    "register_algorithm", "PPO", "PPOConfig", "DQN", "DQNConfig",
    "IMPALA", "IMPALAConfig", "make_env", "register_env",
    "A2C", "A2CConfig", "APPO", "APPOConfig", "SAC", "SACConfig",
    "BC", "BCConfig", "MARWIL", "MARWILConfig", "CQL", "CQLConfig",
    "DDPG", "DDPGConfig", "TD3", "TD3Config",
    "MultiAgentPPO", "MAPPOConfig", "MultiAgentJaxEnv", "CoopMatch",
    "ES", "ESConfig", "LinUCB", "LinUCBConfig", "LinTS", "LinTSConfig",
    "ApexDQN", "ApexDQNConfig",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("rllib")
del _rlu
