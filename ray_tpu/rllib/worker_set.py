"""RolloutWorker actors + WorkerSet.

Counterpart of the reference's `rllib/evaluation/rollout_worker.py:159`
(RolloutWorker.sample :660) and `worker_set.py:80` (WorkerSet:
sync_weights :340, fault-tolerant foreach_worker :634). Used for Python
(non-JAX) envs and for scaling sampling across CPU hosts; JAX envs
normally use the in-graph sampler instead (rollout.py).
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu import exceptions as _exc
from ray_tpu.rllib.rollout import PythonEnvRunner
from ray_tpu.rllib.sample_batch import SampleBatch

logger = logging.getLogger("ray_tpu.rllib")


class RolloutWorker:
    """Actor body: env(s) + policy copy; produces SampleBatches."""

    def __init__(self, env_creator: Callable, module_creator: Callable,
                 rollout_length: int, worker_index: int, seed: int,
                 connectors: dict | None = None, num_envs: int = 1,
                 generation_backend: str | None = None,
                 backend_kwargs: dict | None = None):
        env = env_creator(worker_index)
        from ray_tpu.rllib.env.jax_env import EagerJaxEnv, is_jax_env
        from ray_tpu.rllib.rollout import VectorEnvRunner
        # connector pipelines are host-side transforms and can't run
        # inside the compiled unroll: keep the eager batch-1 runner for
        # them rather than silently dropping the pipeline
        has_connectors = any((connectors or {}).values())
        vectorize = is_jax_env(env) and num_envs > 1 and not has_connectors
        if is_jax_env(env) and num_envs > 1 and has_connectors:
            logger.info(
                "worker %d: connectors configured — using the eager "
                "batch-1 runner instead of the vectorized in-graph "
                "sampler", worker_index)
        if is_jax_env(env) and not vectorize:
            env = EagerJaxEnv(env, seed=seed + worker_index)
        import inspect
        try:
            takes_index = "worker_index" in inspect.signature(
                module_creator).parameters
        except (TypeError, ValueError):
            takes_index = False
        self.module = (module_creator(env, worker_index=worker_index)
                       if takes_index else module_creator(env))
        connectors = connectors or {}
        if generation_backend is not None:
            # pluggable backend (e.g. "engine" -> rl.EngineSampler for
            # token-level envs); gym envs below keep the eager loop.
            from ray_tpu.rllib.rollout import make_env_runner
            self.runner = make_env_runner(
                env, self.module, rollout_length,
                seed=seed + worker_index,
                backend=generation_backend,
                backend_kwargs=backend_kwargs)
        elif vectorize:
            # compiled [T, B] unroll; connectors don't apply in-graph
            self.runner = VectorEnvRunner(
                env, self.module, rollout_length, num_envs,
                seed=seed + worker_index)
        else:
            self.runner = PythonEnvRunner(
                env, self.module, rollout_length,
                seed=seed + worker_index,
                obs_connectors=connectors.get("obs"),
                action_connectors=connectors.get("action"))
        self.params = None

    def set_connector_state(self, state: dict) -> None:
        """Sync learner-side connector state (e.g. a NormalizeObs
        running filter) to this worker (reference: connector state in
        sync_weights)."""
        for key, st in state.items():
            pipe = getattr(self.runner, f"{key}_connectors", None)
            if pipe is not None:
                pipe.set_state(st)

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self) -> tuple:
        """-> (SampleBatch, last_value, episode_stats)"""
        if self.params is None:
            raise RuntimeError("set_weights must be called before sample")
        batch, last_v = self.runner.sample(self.params)
        return batch, last_v, self.runner.pop_episode_stats()

    def sample_with_weights(self, params) -> tuple:
        """One round-trip: sync + sample (the reference splits these; fusing
        halves actor-call latency on the hot path)."""
        self.set_weights(params)
        return self.sample()

    def ping(self) -> bool:
        return True


class WorkerSet:
    """Manages N rollout-worker actors with restart-on-failure
    (reference: WorkerSet + FaultTolerantActorManager,
    `rllib/utils/actor_manager.py`)."""

    def __init__(self, num_workers: int, env_creator: Callable,
                 module_creator: Callable, rollout_length: int,
                 seed: int = 0, num_cpus_per_worker: float = 1.0,
                 max_restarts: int = 2, connectors: dict | None = None,
                 num_envs_per_worker: int = 1,
                 generation_backend: str | None = None,
                 backend_kwargs: dict | None = None):
        self.num_workers = num_workers
        self._make = lambda i: ray_tpu.remote(
            num_cpus=num_cpus_per_worker)(RolloutWorker).remote(
                env_creator, module_creator, rollout_length, i, seed,
                connectors, num_envs_per_worker, generation_backend,
                backend_kwargs)
        self._workers: List = [self._make(i) for i in range(num_workers)]
        self._restarts = [0] * num_workers
        self.max_restarts = max_restarts

    def sample_all(self, params) -> tuple:
        """Parallel sample across all workers; dead workers are restarted
        and skipped this round. -> (batches, last_values, stats_list)"""
        params_ref = ray_tpu.put(_to_host(params))
        futures = {w.sample_with_weights.remote(params_ref): i
                   for i, w in enumerate(self._workers)}
        batches, last_values, stats = [], [], []
        for fut, i in futures.items():
            try:
                b, lv, st = ray_tpu.get(fut, timeout=300)
                batches.append(b)
                last_values.append(lv)
                stats.append(st)
            except (_exc.RayTpuError, TimeoutError) as e:
                logger.warning("rollout worker %d failed: %s; restarting",
                               i, e)
                self._restart(i)
        if not batches:
            raise RuntimeError("all rollout workers failed")
        return batches, last_values, stats

    def _restart(self, i: int) -> None:
        if self._restarts[i] >= self.max_restarts:
            raise RuntimeError(
                f"rollout worker {i} exceeded {self.max_restarts} restarts")
        self._restarts[i] += 1
        try:
            ray_tpu.kill(self._workers[i])
        except _exc.RayTpuError:
            pass
        self._workers[i] = self._make(i)

    def foreach_worker(self, fn_name: str, *args) -> list:
        futs = [getattr(w, fn_name).remote(*args) for w in self._workers]
        return ray_tpu.get(futs, timeout=300)

    def sync_weights(self, params) -> None:
        params_ref = ray_tpu.put(_to_host(params))
        self.foreach_worker("set_weights", params_ref)

    def sync_connector_states(self, state: dict) -> None:
        """Push learner-side connector state (e.g. a NormalizeObs
        running filter, keyed "obs"/"action" -> pipeline.state()) to
        every worker (reference: connector state rides sync_weights)."""
        self.foreach_worker("set_connector_state", state)

    def stop(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except _exc.RayTpuError:
                pass
        self._workers = []


def _to_host(params):
    """Device pytree → numpy pytree (object-store transit is host memory;
    the reference ships torch tensors the same way, worker_set.py:340)."""
    import jax
    return jax.tree.map(np.asarray, params)


def merge_episode_stats(stats_list: List[dict]) -> dict:
    eps = sum(s.get("episodes_this_iter", 0) for s in stats_list)
    rets = [s["episode_reward_mean"] for s in stats_list
            if s.get("episodes_this_iter", 0) > 0]
    lens = [s["episode_len_mean"] for s in stats_list
            if s.get("episodes_this_iter", 0) > 0]
    return {
        "episode_reward_mean": float(np.mean(rets)) if rets
        else float("nan"),
        "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
        "episodes_this_iter": eps,
    }
