"""Rollout collection — the sampling half of every algorithm.

Two paths, mirroring SURVEY.md §2.9's rollout layer but TPU-first:

- `InGraphSampler`: env batch stepped by `vmap`, unrolled by `lax.scan`,
  the whole thing jitted — sampling is a compiled program. This replaces
  the reference's `SyncSampler` Python loop (`rllib/evaluation/sampler.py:144`)
  for JAX-native envs.
- `PythonEnvRunner`: eager loop over arbitrary gym-API Python envs, used
  inside RolloutWorker actors (`rollout_worker.py`) for reference parity.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch


class InGraphSampler:
    """Compiled vectorized rollout for JaxEnv environments."""

    def __init__(self, env, module, num_envs: int, rollout_length: int):
        self.env = env
        self.module = module
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self._unroll = jax.jit(self._unroll_impl)

    def init_state(self, key):
        keys = jax.random.split(key, self.num_envs)
        state, obs = jax.vmap(self.env.reset)(keys)
        return {"env_state": state, "obs": obs,
                "ep_ret": jnp.zeros(self.num_envs),
                "ep_len": jnp.zeros(self.num_envs, jnp.int32)}

    def _unroll_impl(self, params, carry, key):
        """lax.scan over time of a vmapped env step + policy forward."""

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions, logp, value = self.module.compute_actions(
                params, obs, k_act)
            # obs.shape[0], not self.num_envs: under a shard_map'd learner
            # each shard steps its own num_envs/n slice of the env batch
            env_keys = jax.random.split(k_env, obs.shape[0])
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            ep_ret = carry["ep_ret"] + reward
            ep_len = carry["ep_len"] + 1
            # record finished-episode stats, then zero the accumulators
            finished_ret = jnp.where(done, ep_ret, jnp.nan)
            finished_len = jnp.where(done, ep_len, -1)
            new_carry = {
                "env_state": state,
                "obs": next_obs,
                "ep_ret": jnp.where(done, 0.0, ep_ret),
                "ep_len": jnp.where(done, 0, ep_len),
            }
            out = {sb.OBS: obs, sb.ACTIONS: actions, sb.REWARDS: reward,
                   sb.DONES: done, sb.ACTION_LOGP: logp, sb.VF_PREDS: value,
                   "episode_return": finished_ret,
                   "episode_len": finished_len}
            return new_carry, out

        step_keys = jax.random.split(key, self.rollout_length)
        carry, traj = jax.lax.scan(one_step, carry, step_keys)
        # bootstrap value for the final observation of every env
        _, last_value = self.module.forward(params, carry["obs"])
        return carry, traj, last_value

    def sample(self, params, carry, key):
        """-> (new_carry, traj pytree [T, num_envs, ...], last_value
        [num_envs]). Device arrays; algorithms keep them on device."""
        return self._unroll(params, carry, key)


def episode_stats(traj) -> dict:
    """Mean/len of the episodes that finished inside a trajectory."""
    rets = np.asarray(traj["episode_return"]).ravel()
    lens = np.asarray(traj["episode_len"]).ravel()
    done = ~np.isnan(rets)
    if not done.any():
        return {"episode_reward_mean": float("nan"),
                "episode_len_mean": float("nan"), "episodes_this_iter": 0}
    return {
        "episode_reward_mean": float(np.nanmean(rets[done])),
        "episode_len_mean": float(np.mean(lens[done & (lens >= 0)])),
        "episodes_this_iter": int(done.sum()),
    }


class VectorEnvRunner:
    """InGraphSampler inside a rollout actor: the whole [T, B] fragment
    is ONE compiled vmap+scan unroll, so actor-based algorithms (IMPALA,
    Ape-X) get the same per-step cost as the in-graph path instead of a
    per-step eager dispatch. Counterpart of the reference's
    `VectorEnv`/`num_envs_per_worker` sampling
    (`rllib/env/vector_env.py`), minus the Python env loop.

    Batches come back TIME-MAJOR [T, B, ...] with `last_value` [B];
    consumers either keep fragments (V-trace) or flatten to [T*B]
    transitions (replay ingest). `new_obs` rows following a done carry
    the auto-reset observation (masked by (1-done) in TD targets, same
    contract as PythonEnvRunner).
    """

    def __init__(self, env, module, rollout_length: int, num_envs: int,
                 seed: int = 0):
        self.sampler = InGraphSampler(env, module, num_envs,
                                      rollout_length)
        self._key = jax.random.PRNGKey(seed)
        self._carry = None
        self._stats: dict | None = None

    def sample(self, params) -> Tuple[SampleBatch, np.ndarray]:
        self._key, k_init, k_roll = jax.random.split(self._key, 3)
        if self._carry is None:
            self._carry = self.sampler.init_state(k_init)
        self._carry, traj, last_value = self.sampler.sample(
            params, self._carry, k_roll)
        self._stats = episode_stats(traj)
        obs = np.asarray(traj[sb.OBS])
        next_obs = np.concatenate(
            [obs[1:], np.asarray(self._carry["obs"])[None]], axis=0)
        batch = SampleBatch({
            **{k: np.asarray(v) for k, v in traj.items()
               if k not in ("episode_return", "episode_len")},
            sb.NEXT_OBS: next_obs,
        })
        return batch, np.asarray(last_value)

    def pop_episode_stats(self) -> dict:
        stats, self._stats = self._stats, None
        return stats or {"episode_reward_mean": float("nan"),
                         "episode_len_mean": float("nan"),
                         "episodes_this_iter": 0}


# ---------------------------------------------------------------------------
# pluggable generation backends
# ---------------------------------------------------------------------------
# Token-level "envs" (RLHF prompts, best-of-n eval) want the serving
# engine's paged-KV path as their sampler, while gym envs keep the eager
# loop below. A backend is a factory
#   factory(env, module, rollout_length, *, seed, **backend_kwargs)
#     -> runner with sample(params) -> (SampleBatch, last_value)
#        and pop_episode_stats() -> dict
# i.e. the PythonEnvRunner contract. `ray_tpu.rl.sampler` registers
# "engine" (EngineSampler) on import; make_env_runner lazy-imports it so
# rllib never pays for the serving stack unless asked.

_GENERATION_BACKENDS: dict = {}


def register_generation_backend(name: str, factory) -> None:
    """Register a rollout generation backend under `name` (overwrites —
    tests swap in fakes)."""
    _GENERATION_BACKENDS[name] = factory


def make_env_runner(env, module, rollout_length: int, *, seed: int = 0,
                    obs_connectors=None, action_connectors=None,
                    backend: str | None = None,
                    backend_kwargs: dict | None = None):
    """Build a rollout runner. backend=None (the default) is EXACTLY the
    historical PythonEnvRunner construction — a regression test pins the
    default path unchanged. Named backends come from the registry."""
    if backend is None:
        return PythonEnvRunner(env, module, rollout_length, seed=seed,
                               obs_connectors=obs_connectors,
                               action_connectors=action_connectors)
    if backend not in _GENERATION_BACKENDS and backend == "engine":
        from ray_tpu.rl import sampler as _sampler  # noqa: F401
        # import side effect registers "engine"
    try:
        factory = _GENERATION_BACKENDS[backend]
    except KeyError:
        raise ValueError(
            f"unknown generation backend {backend!r} "
            f"(registered: {sorted(_GENERATION_BACKENDS)})") from None
    return factory(env, module, rollout_length, seed=seed,
                   **(backend_kwargs or {}))


class PythonEnvRunner:
    """Eager sampler for gym-API Python envs (reset/step methods).

    `obs_connectors`/`action_connectors` (ray_tpu.rllib.connectors
    pipelines) sit between env and module, the reference's
    agent/action connector placement (rllib/connectors/): obs are
    transformed before the policy sees them (and the TRANSFORMED obs
    land in the batch — training must see what the policy saw); policy
    outputs are transformed before the env steps, with the RAW policy
    action recorded so logp stays consistent."""

    def __init__(self, env, module, rollout_length: int, seed: int = 0,
                 obs_connectors=None, action_connectors=None):
        self.env = env
        self.module = module
        self.rollout_length = rollout_length
        self.obs_connectors = obs_connectors
        self.action_connectors = action_connectors
        self._key = jax.random.PRNGKey(seed)
        self._obs = None
        self._ep_ret = 0.0
        self._ep_len = 0
        self._episode_returns: list = []
        self._episode_lens: list = []
        self._compute = jax.jit(self.module.compute_actions)

    def _connect_obs(self, obs):
        if self.obs_connectors is not None:
            obs = self.obs_connectors(obs)
        return obs

    def _reset_env(self):
        out = self.env.reset()
        self._obs = self._connect_obs(
            out[0] if isinstance(out, tuple) else out)

    def sample(self, params) -> Tuple[SampleBatch, float]:
        if self._obs is None:
            self._reset_env()
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                                sb.ACTION_LOGP, sb.VF_PREDS,
                                sb.NEXT_OBS)}
        for _ in range(self.rollout_length):
            self._key, k = jax.random.split(self._key)
            obs = np.asarray(self._obs, np.float32)
            a, logp, v = self._compute(params, obs[None], k)
            action = np.asarray(a)[0]
            env_action = action
            if self.action_connectors is not None:
                env_action = np.asarray(self.action_connectors(action))
            out = self.env.step(
                env_action.item() if env_action.ndim == 0 else env_action)
            if len(out) == 5:       # gymnasium-style
                nxt, r, term, trunc, _ = out
                done = bool(term or trunc)
            else:
                nxt, r, done, _ = out
            rows[sb.OBS].append(obs)
            rows[sb.ACTIONS].append(action)
            rows[sb.REWARDS].append(np.float32(r))
            rows[sb.DONES].append(done)
            rows[sb.ACTION_LOGP].append(np.asarray(logp)[0])
            rows[sb.VF_PREDS].append(np.asarray(v)[0])
            self._ep_ret += float(r)
            self._ep_len += 1
            if done:
                self._episode_returns.append(self._ep_ret)
                self._episode_lens.append(self._ep_len)
                self._ep_ret, self._ep_len = 0.0, 0
                self._reset_env()
            else:
                self._obs = self._connect_obs(nxt)
            # true successor for TD consumers (done rows are masked by
            # (1-done) in targets, so the auto-reset obs is harmless)
            rows[sb.NEXT_OBS].append(np.asarray(self._obs, np.float32))
        obs = np.asarray(self._obs, np.float32)
        _, _, last_v = self._compute(
            params, obs[None], jax.random.PRNGKey(0))
        batch = SampleBatch({k: np.stack(v) for k, v in rows.items()})
        return batch, float(np.asarray(last_v)[0])

    def pop_episode_stats(self) -> dict:
        stats = {
            "episode_reward_mean": (float(np.mean(self._episode_returns))
                                    if self._episode_returns
                                    else float("nan")),
            "episode_len_mean": (float(np.mean(self._episode_lens))
                                 if self._episode_lens else float("nan")),
            "episodes_this_iter": len(self._episode_returns),
        }
        self._episode_returns, self._episode_lens = [], []
        return stats
