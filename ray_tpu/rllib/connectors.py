"""Connectors: composable obs/action transform pipelines.

Counterpart of the reference's `rllib/connectors/connector.py` (+
`agent/`, `action/` subpackages): the glue between raw env I/O and the
policy is a PIPELINE of small, stateful, serializable transforms rather
than code baked into each policy. Obs connectors run env→module; action
connectors run module→env. Every transform here uses pure array ops, so
the same pipeline works on the eager rollout path (PythonEnvRunner,
PolicyServerInput) AND inside a jitted in-graph sampler.
"""

from __future__ import annotations

from typing import List

import numpy as np


class Connector:
    """One transform. `__call__` maps data -> data; `state`/`set_state`
    carry whatever must sync from learner to rollout workers (reference:
    Connector.serialize/deserialize)."""

    def __call__(self, x):
        raise NotImplementedError

    def state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector] | None = None):
        self.connectors = list(connectors or [])

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c)
        return self

    def state(self) -> dict:
        return {i: c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def __repr__(self):
        return f"ConnectorPipeline({self.connectors})"


# -- obs connectors ----------------------------------------------------------

class FlattenObs(Connector):
    """Dict/tuple/nd observations -> flat f32 vector (reference:
    connectors/agent/obs_preproc.py flattening preprocessor)."""

    def __call__(self, obs):
        if isinstance(obs, dict):
            parts = [np.asarray(obs[k], np.float32).reshape(-1)
                     for k in sorted(obs)]
            return np.concatenate(parts)
        if isinstance(obs, (tuple, list)):
            return np.concatenate(
                [np.asarray(o, np.float32).reshape(-1) for o in obs])
        return np.asarray(obs, np.float32).reshape(-1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        import jax.numpy as jnp
        xp = jnp if not isinstance(obs, np.ndarray) else np
        return xp.clip(obs, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std filter (reference: MeanStdFilter connector).
    Workers apply a FROZEN copy synced from the learner via
    state()/set_state(); the learner side calls update()."""

    def __init__(self, shape=None, eps: float = 1e-8):
        self.count = 0.0
        self.mean = None
        self.m2 = None
        self.eps = eps

    def update(self, obs) -> None:
        x = np.asarray(obs, np.float64)
        if x.ndim == 1:
            x = x[None]
        for row in x:
            self.count += 1.0
            if self.mean is None:
                self.mean = row.copy()
                self.m2 = np.zeros_like(row)
                continue
            delta = row - self.mean
            self.mean += delta / self.count
            self.m2 += delta * (row - self.mean)

    def std(self):
        if self.mean is None or self.count < 2:
            return None
        return np.sqrt(self.m2 / (self.count - 1)) + self.eps

    def __call__(self, obs):
        std = self.std()
        if std is None:
            return obs
        return (np.asarray(obs, np.float32) - self.mean.astype(np.float32)) \
            / std.astype(np.float32)

    def state(self) -> dict:
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state: dict) -> None:
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


# -- action connectors -------------------------------------------------------

class ClipActions(Connector):
    """Clip continuous actions into the env's Box bounds (reference:
    connectors/action/clip.py)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        import jax.numpy as jnp
        xp = jnp if not isinstance(action, np.ndarray) else np
        return xp.clip(action, self.low, self.high)


class UnsquashActions(Connector):
    """Map tanh-squashed [-1, 1] policy outputs onto the Box bounds
    (reference: connectors/action/scale.py unsquash)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        return self.low + (np.asarray(action, np.float32) + 1.0) * 0.5 \
            * (self.high - self.low)


def default_action_pipeline(action_space) -> ConnectorPipeline:
    """The pipeline the reference builds by default: clip continuous
    actions to the space, pass discrete through."""
    from ray_tpu.rllib.env.spaces import Box
    pipe = ConnectorPipeline()
    if isinstance(action_space, Box):
        pipe.append(ClipActions(action_space.low, action_space.high))
    return pipe
