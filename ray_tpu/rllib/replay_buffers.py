"""Replay buffers for off-policy algorithms.

Counterpart of the reference's `rllib/utils/replay_buffers/`
(`replay_buffer.py` ReplayBuffer, `prioritized_replay_buffer.py` +
segment tree `rllib/execution/segment_tree.py`). Host-numpy ring storage
(replay stays in host RAM; only sampled minibatches move to device, the
same division of labor the reference has between plasma and GPU).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over transition columns."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._store: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._store:
            for k, v in batch.items():
                v = np.asarray(v)
                self._store[k] = np.zeros((self.capacity,) + v.shape[1:],
                                          v.dtype)
        idx = (self._idx + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = np.asarray(v)
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._on_added(idx)

    def _on_added(self, idx: np.ndarray) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class _SumTree:
    """Binary indexed sum tree for O(log n) prioritized sampling
    (reference: rllib/execution/segment_tree.py)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        size = 1
        while size < capacity:
            size *= 2
        self.size = size
        self.tree = np.zeros(2 * size, np.float64)

    def set(self, idx: np.ndarray, value: np.ndarray) -> None:
        pos = idx + self.size
        self.tree[pos] = value
        pos //= 2
        # vectorized bottom-up refresh (duplicate parents collapse via
        # unique; loop depth = log2(size))
        while pos[0] >= 1 if len(pos) else False:
            pos = np.unique(pos)
            self.tree[pos] = self.tree[2 * pos] + self.tree[2 * pos + 1]
            if pos[0] == 1:
                break
            pos //= 2

    def total(self) -> float:
        return float(self.tree[1])

    def sample_idx(self, prefix_sums: np.ndarray) -> np.ndarray:
        idx = np.ones(len(prefix_sums), np.int64)
        s = prefix_sums.copy()
        while idx[0] < self.size:
            left = 2 * idx
            go_right = s > self.tree[left]
            s = np.where(go_right, s - self.tree[left], s)
            idx = np.where(go_right, left + 1, left)
        return idx - self.size


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    `prioritized_replay_buffer.py`; Schaul et al. 2016 scheme)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha, self.beta = alpha, beta
        self._tree = _SumTree(self.capacity)
        self._max_priority = 1.0

    def _on_added(self, idx: np.ndarray) -> None:
        self._tree.set(idx,
                       np.full(len(idx), self._max_priority ** self.alpha))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree.total()
        if not np.isfinite(total) or total <= 0.0:
            # degenerate tree (diverged TD errors fed inf priorities, or
            # all-zero): fall back to uniform rather than crash the
            # learner mid-run
            idx = self._rng.integers(0, self._size, batch_size)
        else:
            prefix = self._rng.uniform(0, total, batch_size)
            idx = np.minimum(self._tree.sample_idx(prefix),
                             self._size - 1)
        out = {k: v[idx] for k, v in self._store.items()}
        probs = self._tree.tree[idx + self._tree.size] / max(total, 1e-9)
        weights = (self._size * probs + 1e-9) ** (-self.beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["batch_indexes"] = idx
        return out

    _PRIORITY_CEIL = 1e6    # bounds the tree against diverged TD errors

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        priorities = np.clip(np.nan_to_num(
            priorities, nan=1.0, posinf=self._PRIORITY_CEIL),
            1e-6, self._PRIORITY_CEIL)
        self._max_priority = max(self._max_priority,
                                 float(priorities.max()))
        self._tree.set(np.asarray(idx),
                       priorities.astype(np.float64) ** self.alpha)


class ReplayActor:
    """One shard of a distributed prioritized replay. Ape-X runs N of
    these as actors (reference: `apex_dqn/apex_dqn.py:328-337`
    ReplayActor fleet): rollout workers add round-robin, the learner
    samples shards round-robin and feeds priorities back to the owning
    shard — ingest and sampling scale with shards instead of funneling
    through the learner process."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0,
                 prioritized: bool = True):
        if prioritized:
            self._buf = PrioritizedReplayBuffer(capacity, alpha, beta,
                                                seed=seed)
        else:
            self._buf = ReplayBuffer(capacity, seed=seed)

    def add_batch(self, batch) -> int:
        self._buf.add_batch(batch)
        return len(self._buf)

    def size(self) -> int:
        return len(self._buf)

    def sample(self, batch_size: int):
        if len(self._buf) < batch_size:
            return None
        return self._buf.sample(batch_size)

    def update_priorities(self, idx, priorities) -> bool:
        if isinstance(self._buf, PrioritizedReplayBuffer):
            self._buf.update_priorities(idx, priorities)
        return True
