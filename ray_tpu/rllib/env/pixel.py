"""Pixel-observation environments — the repo's Atari-class oracle tier.

The reference validates its RL stack on Atari via gym + ALE wrappers
(`rllib/env/wrappers/atari_wrappers.py`) and time-to-reward tuned
examples (`rllib/tuned_examples/ppo/pong-ppo.yaml:1`,
`impala/pong-impala-fast.yaml:1-4`). ALE is a C emulator — it cannot be
vmapped or scanned, so a TPU-first framework needs its own pixel tier:
MinAtar-class games (10x10 grids, multi-channel binary images, moving
objects, sparse-ish rewards) written as pure jnp functions. That keeps
the defining difficulty of the Atari oracle — a conv encoder must learn
spatio-temporal structure from pixels — while the whole rollout stays
inside one XLA program (vmap → vector env, lax.scan → unroll), so the
same env runs on the in-graph sampler, the actor path, and an 8-device
mesh unchanged.

Games follow the published MinAtar mechanics (Young & Tian 2019) but are
re-derived and simplified where it does not change the difficulty class;
no code is shared with any emulator.

Observations are [10, 10, C] float32 in {0, 1}; channel semantics are
listed per game. The conv catalog in `core/rl_module.py` picks the conv
torso for these automatically (rank-3 obs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env.jax_env import JaxEnv, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete

_SIZE = 10


def _blank(channels: int):
    return jnp.zeros((_SIZE, _SIZE, channels), jnp.float32)


class PixelBreakout(JaxEnv):
    """Breakout on a 10x10 grid.

    Channels: 0 paddle, 1 ball, 2 ball-trail (previous ball cell — lets a
    feedforward conv infer direction), 3 bricks.

    A 3-row brick wall sits in rows 1-3; the paddle slides on row 9.
    The ball moves one diagonal cell per step, bouncing off walls, bricks
    (+1 reward each) and the paddle; missing the ball ends the episode.
    A cleared wall respawns, so skilled play is unbounded up to the step
    cap. Actions: 0 noop, 1 left, 2 right.
    """

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 500))
        self.observation_space = Box(0.0, 1.0, (_SIZE, _SIZE, 4))
        self.action_space = Discrete(3)

    def _render(self, s):
        obs = _blank(4)
        obs = obs.at[9, s["paddle"], 0].set(1.0)
        obs = obs.at[s["ball_y"], s["ball_x"], 1].set(1.0)
        obs = obs.at[s["last_y"], s["last_x"], 2].set(1.0)
        obs = obs.at[1:4, :, 3].set(s["bricks"].astype(jnp.float32))
        return obs

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        side = jax.random.randint(k1, (), 0, 2)          # spawn corner
        ball_x = jnp.where(side == 0, 0, _SIZE - 1).astype(jnp.int32)
        dx = jnp.where(side == 0, 1, -1).astype(jnp.int32)
        s = {
            "ball_y": jnp.asarray(3, jnp.int32),
            "ball_x": ball_x,
            "last_y": jnp.asarray(3, jnp.int32),
            "last_x": ball_x,
            "dy": jnp.asarray(1, jnp.int32),
            "dx": dx,
            "paddle": jax.random.randint(k2, (), 0, _SIZE),
            "bricks": jnp.ones((3, _SIZE), jnp.int32),
            "t": jnp.asarray(0, jnp.int32),
        }
        return s, self._render(s)

    def step(self, state, action, key):
        s = dict(state)
        action = jnp.asarray(action)
        paddle = jnp.clip(
            s["paddle"] + (action == 2).astype(jnp.int32)
            - (action == 1).astype(jnp.int32), 0, _SIZE - 1)

        # -- ball advance with wall reflection
        nx = s["ball_x"] + s["dx"]
        dx = jnp.where((nx < 0) | (nx >= _SIZE), -s["dx"], s["dx"])
        nx = jnp.clip(jnp.where(nx < 0, -nx, nx), 0, _SIZE - 1)
        ny = s["ball_y"] + s["dy"]
        hit_top = ny < 0
        dy = jnp.where(hit_top, 1, s["dy"])
        ny = jnp.where(hit_top, 1, ny)

        # -- brick collision: bounce back, consume the brick
        in_wall = (ny >= 1) & (ny <= 3)
        brick_row = jnp.clip(ny - 1, 0, 2)
        hit_brick = in_wall & (s["bricks"][brick_row, nx] == 1)
        bricks = s["bricks"].at[brick_row, nx].set(
            jnp.where(hit_brick, 0, s["bricks"][brick_row, nx]))
        reward = hit_brick.astype(jnp.float32)
        dy = jnp.where(hit_brick, -dy, dy)
        ny = jnp.where(hit_brick, s["ball_y"], ny)

        # -- paddle row: catch or miss
        at_bottom = ny >= _SIZE - 1
        caught = at_bottom & (nx == paddle)
        dy = jnp.where(caught, -1, dy)
        missed = at_bottom & ~caught

        # -- cleared wall respawns
        cleared = jnp.all(bricks == 0)
        bricks = jnp.where(cleared, jnp.ones_like(bricks), bricks)

        t = s["t"] + 1
        done = missed | (t >= self.max_steps)
        new = {
            "ball_y": ny, "ball_x": nx,
            "last_y": s["ball_y"], "last_x": s["ball_x"],
            "dy": dy, "dx": dx, "paddle": paddle,
            "bricks": bricks, "t": t,
        }
        reset_state, reset_obs = self.reset(key)
        merged = jax.tree.map(
            lambda r, n: jnp.where(done, r, n), reset_state, new)
        obs = jnp.where(done, reset_obs, self._render(new))
        return merged, obs, reward, done, {}


class PixelAsterix(JaxEnv):
    """Asterix on a 10x10 grid.

    Channels: 0 player, 1 enemy, 2 gold, 3 motion-trail (the cell each
    active entity occupied last move).

    The player walks the middle rows (1-8); one entity per row slides
    across from a random side every few steps — gold pays +1 when
    touched, an enemy ends the episode. Actions: 0 noop, 1 left,
    2 right, 3 up, 4 down.
    """

    _ROWS = 8                      # entity rows 1..8

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 300))
        # difficulty knobs (tuned-example yamls pick easier settings for
        # wall-clock-bounded oracles, like the reference's env_config)
        self._SPAWN_EVERY = int(cfg.get("spawn_every", 3))
        self._MOVE_EVERY = int(cfg.get("move_every", 2))
        self._GOLD_P = float(cfg.get("gold_p", 0.4))
        self.observation_space = Box(0.0, 1.0, (_SIZE, _SIZE, 4))
        self.action_space = Discrete(5)

    def _render(self, s):
        obs = _blank(4)
        obs = obs.at[s["py"], s["px"], 0].set(1.0)
        rows = jnp.arange(self._ROWS) + 1
        act = s["e_active"].astype(jnp.float32)
        enemy = act * (1.0 - s["e_gold"].astype(jnp.float32))
        gold = act * s["e_gold"].astype(jnp.float32)
        obs = obs.at[rows, s["e_x"], 1].add(enemy)
        obs = obs.at[rows, s["e_x"], 2].add(gold)
        trail_x = jnp.clip(s["e_x"] - s["e_dir"], 0, _SIZE - 1)
        obs = obs.at[rows, trail_x, 3].add(act)
        return jnp.clip(obs, 0.0, 1.0)

    def reset(self, key):
        s = {
            "py": jnp.asarray(5, jnp.int32),
            "px": jnp.asarray(5, jnp.int32),
            "e_x": jnp.zeros((self._ROWS,), jnp.int32),
            "e_dir": jnp.ones((self._ROWS,), jnp.int32),
            "e_active": jnp.zeros((self._ROWS,), jnp.bool_),
            "e_gold": jnp.zeros((self._ROWS,), jnp.bool_),
            "spawn_t": jnp.asarray(self._SPAWN_EVERY, jnp.int32),
            "move_t": jnp.asarray(self._MOVE_EVERY, jnp.int32),
            "t": jnp.asarray(0, jnp.int32),
        }
        return s, self._render(s)

    def _collide(self, s, reward, dead):
        """Touch resolution: gold collects, enemy kills."""
        row = s["py"] - 1                      # entity slot for player row
        valid = (s["py"] >= 1) & (s["py"] <= self._ROWS)
        slot_x = s["e_x"][jnp.clip(row, 0, self._ROWS - 1)]
        slot_active = s["e_active"][jnp.clip(row, 0, self._ROWS - 1)]
        slot_gold = s["e_gold"][jnp.clip(row, 0, self._ROWS - 1)]
        touch = valid & slot_active & (slot_x == s["px"])
        reward = reward + (touch & slot_gold).astype(jnp.float32)
        dead = dead | (touch & ~slot_gold)
        s["e_active"] = s["e_active"].at[jnp.clip(row, 0, self._ROWS - 1)] \
            .set(jnp.where(touch, False,
                           s["e_active"][jnp.clip(row, 0,
                                                  self._ROWS - 1)]))
        return s, reward, dead

    def step(self, state, action, key):
        s = dict(state)
        action = jnp.asarray(action)
        k_spawn_row, k_spawn_side, k_spawn_gold, k_reset = \
            jax.random.split(key, 4)

        # -- player move (rows 1..8 only)
        px = jnp.clip(s["px"] + (action == 2).astype(jnp.int32)
                      - (action == 1).astype(jnp.int32), 0, _SIZE - 1)
        py = jnp.clip(s["py"] + (action == 4).astype(jnp.int32)
                      - (action == 3).astype(jnp.int32), 1, self._ROWS)
        s["px"], s["py"] = px, py

        reward = jnp.asarray(0.0)
        dead = jnp.asarray(False)
        s, reward, dead = self._collide(s, reward, dead)

        # -- entity slide every _MOVE_EVERY steps
        move_t = s["move_t"] - 1
        do_move = move_t <= 0
        move_t = jnp.where(do_move, self._MOVE_EVERY, move_t)
        nx = s["e_x"] + jnp.where(do_move, s["e_dir"], 0)
        off = (nx < 0) | (nx >= _SIZE)
        s["e_active"] = s["e_active"] & ~off
        s["e_x"] = jnp.clip(nx, 0, _SIZE - 1)
        s["move_t"] = move_t
        s, reward, dead = self._collide(s, reward, dead)

        # -- spawn into a random row every _SPAWN_EVERY steps
        spawn_t = s["spawn_t"] - 1
        do_spawn = spawn_t <= 0
        spawn_t = jnp.where(do_spawn, self._SPAWN_EVERY, spawn_t)
        row = jax.random.randint(k_spawn_row, (), 0, self._ROWS)
        free = ~s["e_active"][row]
        place = do_spawn & free
        side = jax.random.randint(k_spawn_side, (), 0, 2)
        sx = jnp.where(side == 0, 0, _SIZE - 1).astype(jnp.int32)
        sdir = jnp.where(side == 0, 1, -1).astype(jnp.int32)
        sgold = jax.random.uniform(k_spawn_gold) < self._GOLD_P
        s["e_x"] = s["e_x"].at[row].set(jnp.where(place, sx,
                                                  s["e_x"][row]))
        s["e_dir"] = s["e_dir"].at[row].set(jnp.where(place, sdir,
                                                      s["e_dir"][row]))
        s["e_gold"] = s["e_gold"].at[row].set(
            jnp.where(place, sgold, s["e_gold"][row]))
        s["e_active"] = s["e_active"].at[row].set(
            jnp.where(place, True, s["e_active"][row]))
        s["spawn_t"] = spawn_t

        t = state["t"] + 1
        s["t"] = t
        done = dead | (t >= self.max_steps)
        reset_state, reset_obs = self.reset(k_reset)
        merged = jax.tree.map(
            lambda r, n: jnp.where(done, r, n), reset_state, s)
        obs = jnp.where(done, reset_obs, self._render(s))
        return merged, obs, reward, done, {}


class PixelInvaders(JaxEnv):
    """Space Invaders on a 10x10 grid.

    Channels: 0 player cannon, 1 aliens, 2 friendly bullet,
    3 enemy bullet.

    A 4x6 alien block marches sideways, dropping a row at each edge; the
    cannon on row 9 moves and fires (one bullet in flight, short
    cooldown). Shooting an alien pays +1; an enemy bullet or an alien
    reaching the cannon row ends the episode. A cleared wave respawns.
    Actions: 0 noop, 1 left, 2 right, 3 fire.
    """

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 400))
        self._MOVE_EVERY = int(cfg.get("move_every", 4))
        self._SHOOT_EVERY = int(cfg.get("shoot_every", 6))
        self._COOLDOWN = int(cfg.get("cooldown", 3))
        self.observation_space = Box(0.0, 1.0, (_SIZE, _SIZE, 4))
        self.action_space = Discrete(4)

    @staticmethod
    def _fresh_aliens():
        block = jnp.zeros((_SIZE, _SIZE), jnp.int32)
        return block.at[0:4, 2:8].set(1)

    def _render(self, s):
        obs = _blank(4)
        obs = obs.at[9, s["px"], 0].set(1.0)
        obs = obs.at[:, :, 1].set(s["aliens"].astype(jnp.float32))
        obs = jnp.where(
            s["fb_active"],
            obs.at[s["fb_y"], s["fb_x"], 2].set(1.0), obs)
        obs = jnp.where(
            s["eb_active"],
            obs.at[s["eb_y"], s["eb_x"], 3].set(1.0), obs)
        return obs

    def reset(self, key):
        s = {
            "px": jnp.asarray(5, jnp.int32),
            "aliens": self._fresh_aliens(),
            "adir": jnp.asarray(1, jnp.int32),
            "move_t": jnp.asarray(self._MOVE_EVERY, jnp.int32),
            "shoot_t": jnp.asarray(self._SHOOT_EVERY, jnp.int32),
            "cool": jnp.asarray(0, jnp.int32),
            "fb_y": jnp.asarray(0, jnp.int32),
            "fb_x": jnp.asarray(0, jnp.int32),
            "fb_active": jnp.asarray(False),
            "eb_y": jnp.asarray(0, jnp.int32),
            "eb_x": jnp.asarray(0, jnp.int32),
            "eb_active": jnp.asarray(False),
            "t": jnp.asarray(0, jnp.int32),
        }
        return s, self._render(s)

    def step(self, state, action, key):
        s = dict(state)
        action = jnp.asarray(action)
        k_col, k_reset = jax.random.split(key)

        # -- cannon move + fire
        px = jnp.clip(s["px"] + (action == 2).astype(jnp.int32)
                      - (action == 1).astype(jnp.int32), 0, _SIZE - 1)
        cool = jnp.maximum(s["cool"] - 1, 0)
        fire = (action == 3) & ~s["fb_active"] & (cool == 0)
        fb_y = jnp.where(fire, 8, s["fb_y"])
        fb_x = jnp.where(fire, px, s["fb_x"])
        fb_active = s["fb_active"] | fire
        cool = jnp.where(fire, self._COOLDOWN, cool)

        # -- friendly bullet flight + alien kill
        fb_y = jnp.where(fb_active, fb_y - 1, fb_y)
        fb_off = fb_y < 0
        fb_active = fb_active & ~fb_off
        fb_y = jnp.clip(fb_y, 0, _SIZE - 1)
        hit = fb_active & (s["aliens"][fb_y, fb_x] == 1)
        aliens = s["aliens"].at[fb_y, fb_x].set(
            jnp.where(hit, 0, s["aliens"][fb_y, fb_x]))
        reward = hit.astype(jnp.float32)
        fb_active = fb_active & ~hit

        # -- alien march (sideways; drop + reverse at the walls)
        move_t = s["move_t"] - 1
        do_move = move_t <= 0
        move_t = jnp.where(do_move, self._MOVE_EVERY, move_t)
        cols = jnp.any(aliens == 1, axis=0)
        idx = jnp.arange(_SIZE)
        any_alien = jnp.any(cols)
        left = jnp.min(jnp.where(cols, idx, _SIZE))
        right = jnp.max(jnp.where(cols, idx, -1))
        at_edge = jnp.where(s["adir"] > 0, right >= _SIZE - 1, left <= 0)
        adir = jnp.where(do_move & at_edge & any_alien, -s["adir"],
                         s["adir"])
        drop = do_move & at_edge & any_alien
        shift = do_move & ~at_edge & any_alien
        aliens = jnp.where(shift, jnp.roll(aliens, adir, axis=1), aliens)
        aliens = jnp.where(drop, jnp.roll(aliens, 1, axis=0), aliens)

        # -- enemy fire: random alien column shoots from its lowest row
        shoot_t = s["shoot_t"] - 1
        do_shoot = (shoot_t <= 0) & ~s["eb_active"] & any_alien
        shoot_t = jnp.where(shoot_t <= 0, self._SHOOT_EVERY, shoot_t)
        cols_now = jnp.any(aliens == 1, axis=0)
        ncols = jnp.maximum(jnp.sum(cols_now), 1)
        pick = jax.random.randint(k_col, (), 0, ncols)
        col = jnp.argsort(~cols_now)[pick]       # pick-th active column
        rows = jnp.arange(_SIZE)
        low_row = jnp.max(jnp.where(aliens[:, col] == 1, rows, -1))
        eb_y = jnp.where(do_shoot, jnp.clip(low_row + 1, 0, _SIZE - 1),
                         s["eb_y"])
        eb_x = jnp.where(do_shoot, col, s["eb_x"])
        eb_active = s["eb_active"] | do_shoot

        # -- enemy bullet flight
        eb_y = jnp.where(eb_active & ~do_shoot, eb_y + 1, eb_y)
        eb_off = eb_y >= _SIZE
        eb_y = jnp.clip(eb_y, 0, _SIZE - 1)
        shot_down = eb_active & ~eb_off & (eb_y == 9) & (eb_x == px)
        eb_active = eb_active & ~eb_off & ~shot_down

        # -- wave cleared → new, slightly advanced wave
        cleared = ~jnp.any(aliens == 1)
        aliens = jnp.where(cleared, self._fresh_aliens(), aliens)

        invaded = jnp.any(aliens[9, :] == 1)
        t = s["t"] + 1
        done = shot_down | invaded | (t >= self.max_steps)
        new = {
            "px": px, "aliens": aliens, "adir": adir, "move_t": move_t,
            "shoot_t": shoot_t, "cool": cool,
            "fb_y": fb_y, "fb_x": fb_x, "fb_active": fb_active,
            "eb_y": eb_y, "eb_x": eb_x, "eb_active": eb_active, "t": t,
        }
        reset_state, reset_obs = self.reset(k_reset)
        merged = jax.tree.map(
            lambda r, n: jnp.where(done, r, n), reset_state, new)
        obs = jnp.where(done, reset_obs, self._render(new))
        return merged, obs, reward, done, {}


register_env("PixelBreakout", lambda cfg: PixelBreakout(cfg))
register_env("PixelAsterix", lambda cfg: PixelAsterix(cfg))
register_env("PixelInvaders", lambda cfg: PixelInvaders(cfg))
