"""Multi-agent environments — fixed agent sets as pytree dicts.

Counterpart of the reference's `rllib/env/multi_agent_env.py`
(MultiAgentEnv: per-agent obs/reward dicts, "__all__" done). TPU-native
difference: the agent set is FIXED and known at trace time, so per-agent
dicts are just pytree structure — `jax.vmap` still vectorizes over
environments and `lax.scan` compiles the unroll, exactly like JaxEnv.
Agents entering/leaving mid-episode (the reference supports ragged agent
sets) is out of scope v1: ragged membership means dynamic shapes, which
is the wrong trade on TPU — mask agents out instead.

Contract:
    state, obs = env.reset(key)                  # obs: {agent_id: array}
    state, obs, rewards, done, info = env.step(state, actions, key)
        # actions/rewards: {agent_id: array}; done: scalar — all agents
        # terminate together (mask per-agent activity inside the env)
Auto-reset on done, like JaxEnv.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env.jax_env import register_env
from ray_tpu.rllib.env.spaces import Box, Discrete, Space


class MultiAgentJaxEnv:
    agent_ids: Tuple[str, ...] = ()

    def observation_space(self, agent_id: str) -> Space:
        raise NotImplementedError

    def action_space(self, agent_id: str) -> Space:
        raise NotImplementedError

    def reset(self, key):
        raise NotImplementedError

    def step(self, state, actions: Dict[str, jnp.ndarray], key):
        raise NotImplementedError


def is_multi_agent_env(env) -> bool:
    return isinstance(env, MultiAgentJaxEnv)


class CoopMatch(MultiAgentJaxEnv):
    """Cooperative token-matching with a SHARED reward: each agent
    observes a one-hot token and must pick the matching action, but every
    agent receives the MEAN correctness — the classic shared-reward
    credit-assignment setup (reference's multi-agent learning tests use
    cooperative toys the same way). Optimal per-agent episode return =
    episode_len."""

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.n_agents = int(cfg.get("n_agents", 2))
        self.n_tokens = int(cfg.get("n_tokens", 3))
        self.episode_len = int(cfg.get("episode_len", 8))
        self.agent_ids = tuple(f"agent_{i}" for i in range(self.n_agents))

    def observation_space(self, agent_id: str) -> Space:
        return Box(0.0, 1.0, (self.n_tokens,))

    def action_space(self, agent_id: str) -> Space:
        return Discrete(self.n_tokens)

    def _tokens_to_obs(self, tokens):
        return {aid: jax.nn.one_hot(tokens[i], self.n_tokens)
                for i, aid in enumerate(self.agent_ids)}

    def reset(self, key):
        tokens = jax.random.randint(key, (self.n_agents,), 0, self.n_tokens)
        state = {"tokens": tokens, "t": jnp.asarray(0, jnp.int32)}
        return state, self._tokens_to_obs(tokens)

    def step(self, state, actions, key):
        acts = jnp.stack([actions[aid] for aid in self.agent_ids])
        correct = (acts == state["tokens"]).astype(jnp.float32)
        shared = jnp.mean(correct)
        rewards = {aid: shared for aid in self.agent_ids}
        t = state["t"] + 1
        done = t >= self.episode_len
        k_next, k_reset = jax.random.split(key)
        next_tokens = jax.random.randint(
            k_next, (self.n_agents,), 0, self.n_tokens)
        reset_state, _ = self.reset(k_reset)
        new_state = {
            "tokens": jnp.where(done, reset_state["tokens"], next_tokens),
            "t": jnp.where(done, reset_state["t"], t),
        }
        return (new_state, self._tokens_to_obs(new_state["tokens"]),
                rewards, done, {})


register_env("CoopMatch", lambda cfg: CoopMatch(cfg))
