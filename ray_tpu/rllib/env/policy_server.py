"""External-env RL: policy server + client.

Counterpart of the reference's client-server pattern
(`rllib/env/policy_server_input.py` PolicyServerInput +
`rllib/env/policy_client.py` PolicyClient): the SIMULATOR runs outside
the cluster (a game, a robot, a web service), connects over TCP, asks
the server for actions, and logs rewards; the server turns completed
episodes into `SampleBatch`es that feed an off-policy learner via its
``input_fn`` seam (e.g. ``DQNConfig.offline(input_=server.next_batch)``).

Transport rides `multiprocessing.connection` with an HMAC authkey, like
every other channel in this framework.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import netaddr
from ray_tpu.rllib.sample_batch import (
    ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch, concat_samples)


class _Episode:
    __slots__ = ("obs", "actions", "rewards", "last_obs")

    def __init__(self):
        self.obs: List = []
        self.actions: List = []
        self.rewards: List = []
        self.last_obs = None


class PolicyServerInput:
    """Serve actions to external PolicyClients; collect their experience.

    `compute_action(obs)` must return a single action for a single raw
    observation (typically `algo.compute_single_action`). Obs/action
    connector pipelines (ray_tpu.rllib.connectors) are applied server-
    side, so external simulators send RAW observations."""

    def __init__(self, compute_action, address=("127.0.0.1", 0),
                 authkey: bytes | None = None,
                 obs_connectors=None, action_connectors=None):
        self.compute_action = compute_action
        self.authkey = authkey or os.urandom(16)
        self.obs_connectors = obs_connectors
        self.action_connectors = action_connectors
        self._listener = netaddr.listener(address, self.authkey)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._episodes: Dict[str, _Episode] = {}
        self._complete: List[SampleBatch] = []
        self._steps_ready = 0
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="policy-server-accept").start()

    # -- wiring ---------------------------------------------------------

    @property
    def address(self) -> str:
        return netaddr.bound_address(self._listener)

    def stop(self) -> None:
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._listener.accept()
            except Exception:
                if self._stop:
                    return
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        while not self._stop:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return
            try:
                reply = self._handle(*msg)
            except Exception as e:     # protocol error -> tell the client
                reply = ("error", repr(e))
            try:
                conn.send(reply)
            except (OSError, ValueError):
                return

    # -- protocol -------------------------------------------------------

    def _handle(self, verb, *args):
        if verb == "start_episode":
            eid = uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = _Episode()
            return ("ok", eid)
        if verb == "get_action":
            eid, obs = args
            if self.obs_connectors is not None:
                obs = self.obs_connectors(obs)
            action = self.compute_action(obs)
            if self.action_connectors is not None:
                action = self.action_connectors(action)
            with self._lock:
                ep = self._episodes[eid]
                ep.obs.append(np.asarray(obs))
                ep.actions.append(np.asarray(action))
            return ("ok", action)
        if verb == "log_action":
            # client-side (off-policy) action, e.g. a human or legacy
            # controller driving while we record
            eid, obs, action = args
            if self.obs_connectors is not None:
                obs = self.obs_connectors(obs)
            with self._lock:
                ep = self._episodes[eid]
                ep.obs.append(np.asarray(obs))
                ep.actions.append(np.asarray(action))
            return ("ok", None)
        if verb == "log_returns":
            eid, reward = args
            with self._lock:
                self._episodes[eid].rewards.append(float(reward))
            return ("ok", None)
        if verb == "end_episode":
            eid, last_obs = args
            if self.obs_connectors is not None and last_obs is not None:
                last_obs = self.obs_connectors(last_obs)
            with self._cv:
                ep = self._episodes.pop(eid)
                ep.last_obs = last_obs
                batch = self._episode_to_batch(ep)
                if batch is not None:
                    self._complete.append(batch)
                    self._steps_ready += len(batch)
                    self._cv.notify_all()
            return ("ok", None)
        raise ValueError(f"unknown verb {verb!r}")

    @staticmethod
    def _episode_to_batch(ep: _Episode) -> Optional[SampleBatch]:
        n = min(len(ep.obs), len(ep.actions), len(ep.rewards))
        if n == 0:
            return None
        obs = np.stack(ep.obs[:n])
        nxt = list(ep.obs[1:n])
        nxt.append(np.asarray(ep.last_obs) if ep.last_obs is not None
                   else ep.obs[n - 1])
        dones = np.zeros(n, bool)
        dones[-1] = True
        return SampleBatch({
            OBS: obs.astype(np.float32),
            ACTIONS: np.stack(ep.actions[:n]),
            REWARDS: np.asarray(ep.rewards[:n], np.float32),
            NEXT_OBS: np.stack(nxt).astype(np.float32),
            DONES: dones,
        })

    # -- learner-side ingestion ----------------------------------------

    def next_batch(self, min_steps: int = 1,
                   timeout: float = 60.0) -> SampleBatch:
        """Block until >= min_steps of external experience accumulated;
        returns it all as one batch (the algorithm's input_fn seam)."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._steps_ready < min_steps:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(
                        f"policy server collected {self._steps_ready}/"
                        f"{min_steps} steps within {timeout}s")
                self._cv.wait(min(rem, 0.5))
            batches, self._complete = self._complete, []
            self._steps_ready = 0
        return concat_samples(batches)


class PolicyClient:
    """External-simulator side (reference: rllib/env/policy_client.py)."""

    def __init__(self, address: str, authkey: bytes):
        self._conn = netaddr.client(address, authkey)
        self._lock = threading.Lock()

    def _call(self, *msg):
        with self._lock:
            self._conn.send(msg)
            status, payload = self._conn.recv()
        if status == "error":
            raise RuntimeError(f"policy server error: {payload}")
        return payload

    def start_episode(self) -> str:
        return self._call("start_episode")

    def get_action(self, episode_id: str, obs):
        return self._call("get_action", episode_id, obs)

    def log_action(self, episode_id: str, obs, action) -> None:
        self._call("log_action", episode_id, obs, action)

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call("log_returns", episode_id, reward)

    def end_episode(self, episode_id: str, obs=None) -> None:
        self._call("end_episode", episode_id, obs)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass
