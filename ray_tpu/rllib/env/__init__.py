from ray_tpu.rllib.env.jax_env import (
    CartPole, JaxEnv, Pendulum, make_env, register_env)
from ray_tpu.rllib.env.pixel import (
    PixelAsterix, PixelBreakout, PixelInvaders)
from ray_tpu.rllib.env.spaces import Box, Discrete, Space

__all__ = ["JaxEnv", "CartPole", "Pendulum", "make_env", "register_env",
           "Box", "Discrete", "Space",
           "PixelBreakout", "PixelAsterix", "PixelInvaders"]
