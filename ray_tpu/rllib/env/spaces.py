"""Observation/action spaces.

Minimal gym-compatible space types (the reference depends on `gym.spaces`
throughout, e.g. `rllib/env/base_env.py`; this image ships no gym, and the
framework only needs shape/dtype/bounds metadata + sampling).
"""

from __future__ import annotations

import numpy as np


class Space:
    shape: tuple = ()
    dtype = np.float32

    def sample(self, rng: np.random.Generator | None = None):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Discrete(Space):
    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int32

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n

    def __repr__(self):
        return f"Discrete({self.n})"


class Box(Space):
    def __init__(self, low, high, shape=None, dtype=np.float32):
        if shape is None:
            shape = np.broadcast(np.asarray(low), np.asarray(high)).shape
        self.shape = tuple(shape)
        self.low = np.broadcast_to(np.asarray(low, dtype), self.shape)
        self.high = np.broadcast_to(np.asarray(high, dtype), self.shape)
        self.dtype = dtype

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        low = np.where(np.isfinite(self.low), self.low, -1.0)
        high = np.where(np.isfinite(self.high), self.high, 1.0)
        return rng.uniform(low, high).astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape and \
            bool(np.all(x >= self.low - 1e-6)) and \
            bool(np.all(x <= self.high + 1e-6))

    def __repr__(self):
        return f"Box{self.shape}"
