"""JaxEnv — environments as pure functions, the TPU-native env API.

The reference's env stack (`rllib/env/`: BaseEnv/VectorEnv/MultiAgentEnv)
vectorizes by running many Python envs; here the env itself is a pair of
pure functions, so `jax.vmap` gives a vector env and `lax.scan` gives a
compiled unroll — whole-rollout-on-device, something the reference cannot
express (SURVEY.md §2.4: its parallelism is orchestration-level).

Contract (gymnax-style):
    state, obs = env.reset(key)
    state, obs, reward, done, info = env.step(state, action, key)

Both must be jit-traceable; `state` is an arbitrary pytree. Auto-reset on
done happens inside `step` so scans never branch on python bools.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.env.spaces import Box, Discrete, Space


class JaxEnv:
    """Subclass and implement reset_fn/step_fn + spaces."""

    observation_space: Space
    action_space: Space

    def reset(self, key) -> Tuple[Any, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state, action, key):
        """Returns (state, obs, reward, done, info). Must auto-reset."""
        raise NotImplementedError


_ENV_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register_env(name: str, creator: Callable[..., Any]) -> None:
    """Reference: `ray.tune.registry.register_env` (used by all RLlib
    examples to look envs up by string id)."""
    _ENV_REGISTRY[name] = creator


def make_env(spec, env_config: dict | None = None):
    """Resolve an env from a string id, creator callable, class, or
    instance."""
    env_config = env_config or {}
    if isinstance(spec, str):
        if spec not in _ENV_REGISTRY:
            raise KeyError(
                f"unknown env {spec!r}; register it with "
                f"ray_tpu.rllib.register_env (known: "
                f"{sorted(_ENV_REGISTRY)})")
        return _ENV_REGISTRY[spec](env_config)
    if isinstance(spec, type):
        return spec(**env_config) if env_config else spec()
    if callable(spec) and not hasattr(spec, "step"):
        return spec(env_config)
    return spec


def is_jax_env(env) -> bool:
    return isinstance(env, JaxEnv)


# ---------------------------------------------------------------------------
# Classic-control environments (dynamics follow the standard OpenAI Gym
# definitions; implemented from the published equations, in jnp)
# ---------------------------------------------------------------------------


class CartPole(JaxEnv):
    """CartPole-v1 dynamics. Episode caps at 500 steps, reward 1/step."""

    max_steps = 500

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 500))
        self.observation_space = Box(-jnp.inf, jnp.inf, (4,))
        self.action_space = Discrete(2)

    def reset(self, key):
        obs = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        state = {"obs": obs, "t": jnp.asarray(0, jnp.int32)}
        return state, obs

    def _physics(self, obs, action):
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5                     # half pole length
        polemass_length = masspole * length
        force_mag, tau = 10.0, 0.02

        x, x_dot, theta, theta_dot = obs[0], obs[1], obs[2], obs[3]
        force = jnp.where(action == 1, force_mag, -force_mag)
        costh, sinth = jnp.cos(theta), jnp.sin(theta)
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        thetaacc = (gravity * sinth - costh * temp) / (
            length * (4.0 / 3.0 - masspole * costh ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costh / total_mass
        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        return jnp.stack([x, x_dot, theta, theta_dot])

    def step(self, state, action, key):
        obs = self._physics(state["obs"], action)
        t = state["t"] + 1
        x, theta = obs[0], obs[2]
        failed = (jnp.abs(x) > 2.4) | (jnp.abs(theta) > 12 * jnp.pi / 180)
        done = failed | (t >= self.max_steps)
        reward = jnp.asarray(1.0)
        # auto-reset: where done, swap in a fresh episode. Explicitly the
        # PARENT reset: observation-masking subclasses
        # (StatelessCartPole) override reset() at the boundary, but the
        # internal state swap needs the full 4-dim observation
        reset_state, reset_obs = CartPole.reset(self, key)
        new_obs = jnp.where(done, reset_obs, obs)
        new_t = jnp.where(done, reset_state["t"], t)
        return ({"obs": new_obs, "t": new_t}, new_obs, reward, done, {})


class Pendulum(JaxEnv):
    """Pendulum-v1: continuous control, torque in [-2, 2]."""

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 200))
        self.observation_space = Box(-jnp.inf, jnp.inf, (3,))
        self.action_space = Box(-2.0, 2.0, (1,))

    def _obs(self, th, thdot):
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def reset(self, key):
        k1, k2 = jax.random.split(key)
        th = jax.random.uniform(k1, minval=-jnp.pi, maxval=jnp.pi)
        thdot = jax.random.uniform(k2, minval=-1.0, maxval=1.0)
        state = {"th": th, "thdot": thdot, "t": jnp.asarray(0, jnp.int32)}
        return state, self._obs(th, thdot)

    def step(self, state, action, key):
        g, m, l, dt = 10.0, 1.0, 1.0, 0.05
        u = jnp.clip(jnp.squeeze(action), -2.0, 2.0)
        th, thdot = state["th"], state["thdot"]
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * g / (2 * l) * jnp.sin(th)
                         + 3.0 / (m * l ** 2) * u) * dt
        thdot = jnp.clip(thdot, -8.0, 8.0)
        th = th + thdot * dt
        t = state["t"] + 1
        done = t >= self.max_steps
        reset_state, reset_obs = self.reset(key)
        new = {
            "th": jnp.where(done, reset_state["th"], th),
            "thdot": jnp.where(done, reset_state["thdot"], thdot),
            "t": jnp.where(done, reset_state["t"], t),
        }
        obs = jnp.where(done, reset_obs, self._obs(th, thdot))
        return new, obs, -cost, done, {}


class Acrobot(JaxEnv):
    """Acrobot-v1: swing a two-link pendulum's tip above the bar — a
    genuinely harder task than CartPole (long horizon, sparse -1/step
    reward, needs energy pumping). Dynamics follow the Sutton & Barto
    formulation used by the standard Gym env, RK4-integrated; written
    from the published equations in jnp."""

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.max_steps = int(cfg.get("max_steps", 500))
        self.observation_space = Box(-jnp.inf, jnp.inf, (6,))
        self.action_space = Discrete(3)

    _M1 = _M2 = 1.0       # link masses
    _L1 = 1.0             # link 1 length
    _LC1 = _LC2 = 0.5     # centers of mass
    _I1 = _I2 = 1.0       # moments of inertia
    _G = 9.8
    _DT = 0.2
    _MAX_V1 = 4 * jnp.pi
    _MAX_V2 = 9 * jnp.pi

    def _obs(self, s):
        t1, t2, d1, d2 = s[0], s[1], s[2], s[3]
        return jnp.stack([jnp.cos(t1), jnp.sin(t1),
                          jnp.cos(t2), jnp.sin(t2), d1, d2])

    def reset(self, key):
        s = jax.random.uniform(key, (4,), minval=-0.1, maxval=0.1)
        state = {"s": s, "t": jnp.asarray(0, jnp.int32)}
        return state, self._obs(s)

    def _dsdt(self, s_aug):
        m1, m2, l1 = self._M1, self._M2, self._L1
        lc1, lc2, i1, i2, g = self._LC1, self._LC2, self._I1, self._I2, \
            self._G
        t1, t2, dt1, dt2, a = (s_aug[0], s_aug[1], s_aug[2], s_aug[3],
                               s_aug[4])
        d1 = (m1 * lc1 ** 2 + m2 * (l1 ** 2 + lc2 ** 2
                                    + 2 * l1 * lc2 * jnp.cos(t2))
              + i1 + i2)
        d2 = m2 * (lc2 ** 2 + l1 * lc2 * jnp.cos(t2)) + i2
        phi2 = m2 * lc2 * g * jnp.cos(t1 + t2 - jnp.pi / 2.0)
        phi1 = (-m2 * l1 * lc2 * dt2 ** 2 * jnp.sin(t2)
                - 2 * m2 * l1 * lc2 * dt2 * dt1 * jnp.sin(t2)
                + (m1 * lc1 + m2 * l1) * g * jnp.cos(t1 - jnp.pi / 2.0)
                + phi2)
        ddt2 = ((a + d2 / d1 * phi1
                 - m2 * l1 * lc2 * dt1 ** 2 * jnp.sin(t2) - phi2)
                / (m2 * lc2 ** 2 + i2 - d2 ** 2 / d1))
        ddt1 = -(d2 * ddt2 + phi1) / d1
        return jnp.stack([dt1, dt2, ddt1, ddt2, jnp.zeros_like(a)])

    def _rk4(self, s, torque):
        y0 = jnp.concatenate([s, torque[None]])
        dt = self._DT
        k1 = self._dsdt(y0)
        k2 = self._dsdt(y0 + dt / 2 * k1)
        k3 = self._dsdt(y0 + dt / 2 * k2)
        k4 = self._dsdt(y0 + dt * k3)
        y = y0 + dt / 6.0 * (k1 + 2 * k2 + 2 * k3 + k4)
        return y[:4]

    def step(self, state, action, key):
        torque = jnp.asarray(action, jnp.float32) - 1.0   # {-1, 0, +1}
        s = self._rk4(state["s"], torque)
        wrap = lambda x: ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi  # noqa:E731
        s = jnp.stack([
            wrap(s[0]), wrap(s[1]),
            jnp.clip(s[2], -self._MAX_V1, self._MAX_V1),
            jnp.clip(s[3], -self._MAX_V2, self._MAX_V2)])
        t = state["t"] + 1
        solved = -jnp.cos(s[0]) - jnp.cos(s[1] + s[0]) > 1.0
        done = solved | (t >= self.max_steps)
        reward = jnp.where(solved, 0.0, -1.0)
        reset_state, reset_obs = self.reset(key)
        new_s = jnp.where(done, reset_state["s"], s)
        new_t = jnp.where(done, reset_state["t"], t)
        obs = jnp.where(done, reset_obs, self._obs(s))
        return ({"s": new_s, "t": new_t}, obs, reward, done, {})


class EagerJaxEnv:
    """Gym-API adapter over a JaxEnv, for actor-based rollout workers
    (the reference's RolloutWorker steps gym envs eagerly; this lets the
    same JaxEnv serve both the in-graph and the actor path)."""

    def __init__(self, env: JaxEnv, seed: int = 0):
        self.env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self._key = jax.random.PRNGKey(seed)
        self._reset = jax.jit(env.reset)
        self._step = jax.jit(env.step)
        self._state = None

    def _split(self):
        self._key, k = jax.random.split(self._key)
        return k

    def reset(self):
        self._state, obs = self._reset(self._split())
        return np.asarray(obs)

    def step(self, action):
        self._state, obs, r, done, info = self._step(
            self._state, jnp.asarray(action), self._split())
        return np.asarray(obs), float(r), bool(done), info


class StatelessCartPole(CartPole):
    """CartPole with the velocity components masked out — position and
    angle only, so the policy must INFER velocities from memory. The
    classic recurrent-policy benchmark (reference:
    rllib/examples/env/stateless_cartpole.py).

    Masking happens strictly at the OBSERVATION boundary: the internal
    state (and the parent's auto-reset, which calls the PARENT reset
    explicitly) stays 4-dimensional."""

    def __init__(self, env_config: dict | None = None):
        super().__init__(env_config)
        self.observation_space = Box(-jnp.inf, jnp.inf, (2,))

    @staticmethod
    def _mask(obs):
        return jnp.stack([obs[0], obs[2]])   # x, theta (no derivatives)

    def reset(self, key):
        state, obs = CartPole.reset(self, key)
        return state, self._mask(obs)

    def step(self, state, action, key):
        state, obs, r, done, info = CartPole.step(self, state, action,
                                                  key)
        return state, self._mask(obs), r, done, info


class MemoryRecall(JaxEnv):
    """Memory probe: a one-hot cue is shown ONLY at t=0; matching the
    cue's action pays 1 every step for the rest of the episode. The
    memoryless ceiling is ~(1 + (T-1)/2) in expectation, so beating it
    requires carrying the cue in recurrent state (reference analogue:
    rllib/examples/env/repeat_after_me_env.py)."""

    def __init__(self, env_config: dict | None = None):
        cfg = env_config or {}
        self.episode_len = int(cfg.get("episode_len", 10))
        # obs = [cue0, cue1, t/T]; cue channels nonzero only at t=0
        self.observation_space = Box(-jnp.inf, jnp.inf, (3,))
        self.action_space = Discrete(2)

    def _obs(self, cue, t):
        show = (t == 0).astype(jnp.float32)
        onehot = jax.nn.one_hot(cue, 2) * show
        return jnp.concatenate(
            [onehot, (t / self.episode_len)[None].astype(jnp.float32)])

    def reset(self, key):
        cue = jax.random.randint(key, (), 0, 2)
        t = jnp.asarray(0, jnp.int32)
        return {"cue": cue, "t": t}, self._obs(cue, t)

    def step(self, state, action, key):
        reward = (action == state["cue"]).astype(jnp.float32)
        t = state["t"] + 1
        done = t >= self.episode_len
        reset_state, reset_obs = self.reset(key)
        new_state = {"cue": jnp.where(done, reset_state["cue"],
                                      state["cue"]),
                     "t": jnp.where(done, reset_state["t"], t)}
        obs = jnp.where(done, reset_obs,
                        self._obs(new_state["cue"], new_state["t"]))
        return new_state, obs, reward, done, {}


register_env("CartPole-v1", lambda cfg: CartPole(cfg))
register_env("Pendulum-v1", lambda cfg: Pendulum(cfg))
register_env("Acrobot-v1", lambda cfg: Acrobot(cfg))
register_env("StatelessCartPole", lambda cfg: StatelessCartPole(cfg))
register_env("MemoryRecall", lambda cfg: MemoryRecall(cfg))
