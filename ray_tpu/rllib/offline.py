"""Offline RL IO + off-policy estimation.

Counterpart of the reference's `rllib/offline/`: `json_writer.py` /
`json_reader.py` (SampleBatches as JSONL shards), `InputReader` iteration,
`dataset_reader.py` (offline data through the Data library), and the
off-policy estimators `offline/estimators/` — ImportanceSampling,
WeightedImportanceSampling (IS/WIS per Precup 2000), DirectMethod and
DoublyRobust (Jiang & Li 2016) backed by Fitted Q Evaluation (Le et al.
2019; reference: `offline/estimators/fqe_torch_model.py`, here a jitted
flax/optax loop). Batches are stored row-compressed as JSON with base64
numpy columns, one batch per line, so shards stream without loading
everything.
"""

from __future__ import annotations

import base64
import glob
import io
import json
import os
from typing import Iterator, List

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


def _encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__npy__": base64.b64encode(buf.getvalue()).decode()}


def _decode(obj):
    if isinstance(obj, dict) and "__npy__" in obj:
        return np.load(io.BytesIO(base64.b64decode(obj["__npy__"])),
                       allow_pickle=False)
    return obj


class JsonWriter:
    """Append SampleBatches to JSONL shards (reference: json_writer.py)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._shard = 0
        self._f = None

    def _file(self):
        if self._f is None or self._f.tell() > self.max_file_size:
            if self._f:
                self._f.close()
            self._f = open(os.path.join(
                self.path, f"output-{self._shard:05d}.jsonl"), "a")
            self._shard += 1
        return self._f

    def write(self, batch: SampleBatch) -> None:
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        f = self._file()
        f.write(json.dumps(row) + "\n")
        f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


class JsonReader:
    """Stream SampleBatches back from JSONL shards
    (reference: json_reader.py). `next()` cycles forever, like the
    reference's bandit-style input readers."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "*.jsonl")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"no offline shards at {path!r}")
        self._iter = self._rows()

    def _rows(self) -> Iterator[SampleBatch]:
        while True:
            for fn in self.files:
                with open(fn) as f:
                    for line in f:
                        if not line.strip():
                            continue
                        row = json.loads(line)
                        yield SampleBatch(
                            {k: _decode(v) for k, v in row.items()})

    def next(self) -> SampleBatch:
        return next(self._iter)

    def read_all(self) -> SampleBatch:
        out: List[SampleBatch] = []
        for fn in self.files:
            with open(fn) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    out.append(SampleBatch(
                        {k: _decode(v) for k, v in row.items()}))
        return concat_samples(out)


class DatasetReader:
    """Offline input through a `ray_tpu.data.Dataset` (reference:
    `rllib/offline/dataset_reader.py` — the reference reads offline data
    with Ray Data readers, so JSON/parquet/csv sources, repartitioning
    and streaming all come for free). Rows must carry SampleBatch
    columns (`obs`, `actions`, `rewards`, `dones`, `action_logp`, ...).

    `next()` cycles minibatches forever (InputReader contract);
    `read_all()` materializes the full dataset as one SampleBatch.
    """

    def __init__(self, dataset, batch_size: int = 256):
        self.dataset = dataset
        self.batch_size = batch_size
        self._iter = None

    def _batches(self):
        while True:
            for cols in self.dataset.iter_batches(
                    batch_size=self.batch_size, batch_format="numpy"):
                yield SampleBatch(
                    {k: np.asarray(v) for k, v in cols.items()})

    def next(self) -> SampleBatch:
        if self._iter is None:
            self._iter = self._batches()
        return next(self._iter)

    def read_all(self) -> SampleBatch:
        cols = self.dataset.to_numpy()
        return SampleBatch({k: np.asarray(v) for k, v in cols.items()})


def actions_to_unit(actions, low, high) -> np.ndarray:
    """Env-scaled dataset actions -> the actor's tanh range [-1, 1],
    clipped just inside the boundary so log-prob/atanh-style losses stay
    finite. Shared by the offline continuous-control algorithms
    (CQL, CRR)."""
    actions = np.asarray(actions, np.float32)
    return np.clip(2.0 * (actions - low) / (high - low) - 1.0,
                   -0.999, 0.999)


def resolve_input(input_):
    """Normalize an algorithm's offline `input_` config to a reader
    (reference: `rllib/offline/io_context.py` input resolution): a
    path/glob → JsonReader, a `ray_tpu.data.Dataset` → DatasetReader;
    readers (anything with .next()) and callables pass through."""
    if isinstance(input_, str):
        return JsonReader(input_)
    if hasattr(input_, "iter_batches") and hasattr(input_, "to_numpy"):
        return DatasetReader(input_)
    return input_


# ---------------------------------------------------------------------------
# off-policy estimators (reference: rllib/offline/estimators/)
# ---------------------------------------------------------------------------

def _per_episode(batch: SampleBatch):
    """Episodes of a batch (SampleBatch.split_by_episode, which handles
    both EPS_ID boundaries and the DONES fallback)."""
    if not isinstance(batch, SampleBatch):
        batch = SampleBatch(batch)
    return batch.split_by_episode()


def importance_sampling(batch: SampleBatch, target_logp: np.ndarray,
                        gamma: float = 1.0) -> dict:
    """Ordinary IS estimate of the target policy's value from behaviour
    data (reference: estimators/importance_sampling.py). `target_logp` is
    the target policy's log-prob of the logged actions, aligned to batch
    rows; the behaviour log-prob comes from the logged ACTION_LOGP."""
    behaviour_logp = np.asarray(batch[sb.ACTION_LOGP])
    vals, raw = [], []
    offset = 0
    for ep in _per_episode(batch):
        t = len(ep[sb.REWARDS])
        lp_t = target_logp[offset:offset + t]
        lp_b = behaviour_logp[offset:offset + t]
        offset += t
        w = np.exp(np.cumsum(lp_t - lp_b))       # per-step products
        disc = gamma ** np.arange(t)
        vals.append(float(np.sum(w * disc * ep[sb.REWARDS])))
        raw.append(float(np.sum(disc * ep[sb.REWARDS])))
    return {"v_target": float(np.mean(vals)),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(np.mean(vals) / (np.mean(raw) + 1e-8))}


class FittedQEvaluation:
    """FQE (Le et al. 2019): fit Q^π of the TARGET policy on behaviour
    data by iterating the Bellman backup with a frozen target network.
    Counterpart of the reference's
    `offline/estimators/fqe_torch_model.py`, as a jitted flax/optax loop.

    Discrete actions. `fit(batch, target_probs)` needs `new_obs` rows;
    `target_probs` is π(a|s) of the evaluated policy, [N, A].
    """

    def __init__(self, obs_shape, num_actions: int,
                 hiddens=(64, 64), lr: float = 1e-2, gamma: float = 0.99,
                 n_iters: int = 40, sgd_steps_per_iter: int = 10,
                 seed: int = 0):
        import flax.linen as nn
        import jax
        import optax

        class _Q(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = x.reshape(x.shape[0], -1)
                for h in hiddens:
                    x = nn.relu(nn.Dense(h)(x))
                return nn.Dense(num_actions)(x)

        self.gamma = gamma
        self.n_iters = n_iters
        self.sgd_steps = sgd_steps_per_iter
        self._net = _Q()
        dummy = np.zeros((1, int(np.prod(obs_shape))), np.float32)
        self.params = self._net.init(
            jax.random.PRNGKey(seed), dummy)["params"]
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self.params)

        import jax.numpy as jnp

        def q_fn(params, obs):
            return self._net.apply({"params": params}, obs)

        def update(params, opt_state, obs, act, targets):
            def loss_fn(p):
                q_sa = jnp.take_along_axis(
                    q_fn(p, obs), act[:, None], axis=-1)[:, 0]
                return jnp.mean(jnp.square(q_sa - targets))
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._q_fn = jax.jit(q_fn)
        self._update = jax.jit(update)

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32).reshape(len(obs), -1)
        return np.asarray(self._q_fn(self.params, obs))

    def v_values(self, obs: np.ndarray,
                 target_probs: np.ndarray) -> np.ndarray:
        """V^π(s) = Σ_a π(a|s) Q(s, a)."""
        return (self.q_values(obs) * np.asarray(target_probs)).sum(-1)

    def fit(self, batch: SampleBatch, target_probs: np.ndarray,
            target_probs_next: np.ndarray | None = None) -> dict:
        """`target_probs` is π(a|s) on the batch's `obs` rows;
        `target_probs_next` is π(a|s') on its `new_obs` rows — REQUIRED
        for a state-dependent policy (the Bellman backup bootstraps
        V(s') = Σ_a π(a|s') Q(s', a)). When omitted, `target_probs` is
        reused, which is only exact for state-independent policies."""
        import jax.numpy as jnp

        obs = np.asarray(batch[sb.OBS], np.float32)
        obs = obs.reshape(len(obs), -1)
        nxt = np.asarray(batch[sb.NEXT_OBS], np.float32)
        nxt = nxt.reshape(len(nxt), -1)
        act = np.asarray(batch[sb.ACTIONS], np.int32)
        rew = np.asarray(batch[sb.REWARDS], np.float32)
        done = np.asarray(batch[sb.DONES], np.float32)
        probs_next = np.asarray(
            target_probs if target_probs_next is None
            else target_probs_next, np.float32)
        losses = []
        loss = float("nan")
        for _ in range(self.n_iters):
            # Bellman targets from the FROZEN iterate
            q_next = np.asarray(self._q_fn(self.params, jnp.asarray(nxt)))
            v_next = (q_next * probs_next).sum(-1)
            targets = jnp.asarray(rew + self.gamma * (1.0 - done) * v_next)
            for _ in range(self.sgd_steps):
                self.params, self._opt_state, loss = self._update(
                    self.params, self._opt_state, jnp.asarray(obs),
                    jnp.asarray(act), targets)
            losses.append(float(loss))
        return {"loss": losses[-1] if losses else float(loss),
                "losses": losses}


def direct_method(batch: SampleBatch, target_probs: np.ndarray,
                  q_model: FittedQEvaluation,
                  gamma: float = 1.0) -> dict:
    """DM (reference: `offline/estimators/direct_method.py`): the target
    policy's value is the fitted model's V^π at episode starts — no
    importance weights, so low variance but biased by model error."""
    episodes = _per_episode(batch)
    # only episode-START values are consumed: evaluate the model there
    starts = np.cumsum([0] + [len(ep[sb.REWARDS])
                              for ep in episodes[:-1]])
    obs0 = np.asarray(batch[sb.OBS])[starts]
    v0 = q_model.v_values(obs0, np.asarray(target_probs)[starts])
    vals, raw = [], []
    for i, ep in enumerate(episodes):
        t = len(ep[sb.REWARDS])
        vals.append(float(v0[i]))
        raw.append(float(np.sum(gamma ** np.arange(t) * ep[sb.REWARDS])))
    return {"v_target": float(np.mean(vals)),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(np.mean(vals) / (np.mean(raw) + 1e-8))}


def doubly_robust(batch: SampleBatch, target_logp: np.ndarray,
                  target_probs: np.ndarray,
                  q_model: FittedQEvaluation,
                  gamma: float = 1.0) -> dict:
    """Weighted doubly-robust estimation (WDR, Thomas & Brunskill 2016,
    eqn 10; reference: `offline/estimators/doubly_robust.py`, which
    likewise defaults to the self-normalized weights):

        V_WDR = Σ_i Σ_t γ^t [ w_t^i r_t^i − w_t^i Q̂(s_t^i, a_t^i)
                              + w_{t−1}^i V̂(s_t^i) ]

    where w_t^i = ρ_{0:t}^i / Σ_j ρ_{0:t}^j is the cumulative importance
    weight of episode i self-normalized over the episodes still alive at
    step t (w_{−1}^i = 1/n). Plain DR (per-step ρ, no normalization) is
    unbiased if EITHER the model or the weights are right, but when the
    model is wrong its correction term inherits the full variance of the
    weights; self-normalizing trades a vanishing bias for a large
    variance cut, so a wrong model degrades gracefully instead of
    swinging the estimate."""
    behaviour_logp = np.asarray(batch[sb.ACTION_LOGP])
    obs = np.asarray(batch[sb.OBS])
    act = np.asarray(batch[sb.ACTIONS], np.int64)
    q_all = q_model.q_values(obs)
    v_all = (q_all * np.asarray(target_probs)).sum(-1)
    q_sa = np.take_along_axis(q_all, act[:, None], axis=-1)[:, 0]
    eps, raw = [], []
    offset = 0
    for ep in _per_episode(batch):
        t = len(ep[sb.REWARDS])
        sl = slice(offset, offset + t)
        w = np.exp(np.cumsum(target_logp[sl] - behaviour_logp[sl]))
        r = np.asarray(ep[sb.REWARDS])
        eps.append((w, r, v_all[sl], q_sa[sl]))
        raw.append(float(np.sum(gamma ** np.arange(t) * r)))
        offset += t
    n = len(eps)
    max_t = max(len(w) for w, _, _, _ in eps)
    norm = np.zeros(max_t)
    for w, _, _, _ in eps:
        norm[:len(w)] += w
    norm = np.maximum(norm, 1e-8)
    v_target = 0.0
    for w, r, v_hat, q_hat in eps:
        t = len(w)
        disc = gamma ** np.arange(t)
        wt = w / norm[:t]
        wtm1 = np.concatenate([[1.0 / n], wt[:-1]])
        v_target += float(np.sum(disc * (wt * r - wt * q_hat
                                         + wtm1 * v_hat)))
    return {"v_target": float(v_target),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(v_target / (np.mean(raw) + 1e-8))}


def weighted_importance_sampling(batch: SampleBatch,
                                 target_logp: np.ndarray,
                                 gamma: float = 1.0) -> dict:
    """WIS: weights normalized by the per-timestep mean weight across
    episodes (reference: estimators/weighted_importance_sampling.py) —
    biased but far lower variance than IS."""
    behaviour_logp = np.asarray(batch[sb.ACTION_LOGP])
    eps = []
    offset = 0
    for ep in _per_episode(batch):
        t = len(ep[sb.REWARDS])
        lp_t = target_logp[offset:offset + t]
        lp_b = behaviour_logp[offset:offset + t]
        offset += t
        eps.append((np.exp(np.cumsum(lp_t - lp_b)), ep[sb.REWARDS]))
    max_t = max(len(w) for w, _ in eps)
    # per-timestep normalizer over episodes still alive at t
    norm = np.zeros(max_t)
    cnt = np.zeros(max_t)
    for w, _ in eps:
        norm[:len(w)] += w
        cnt[:len(w)] += 1
    norm = norm / np.maximum(cnt, 1)
    vals, raw = [], []
    for w, r in eps:
        t = len(w)
        disc = gamma ** np.arange(t)
        vals.append(float(np.sum(w / (norm[:t] + 1e-8) * disc * r)))
        raw.append(float(np.sum(disc * r)))
    return {"v_target": float(np.mean(vals)),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(np.mean(vals) / (np.mean(raw) + 1e-8))}
