"""Offline RL IO + off-policy estimation.

Counterpart of the reference's `rllib/offline/`: `json_writer.py` /
`json_reader.py` (SampleBatches as JSONL shards), `InputReader` iteration,
and the off-policy estimators `offline/estimators/` (ImportanceSampling,
WeightedImportanceSampling — IS/WIS per Precup 2000). Batches are stored
row-compressed as JSON with base64 numpy columns, one batch per line, so
shards stream without loading everything.
"""

from __future__ import annotations

import base64
import glob
import io
import json
import os
from typing import Iterator, List

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


def _encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__npy__": base64.b64encode(buf.getvalue()).decode()}


def _decode(obj):
    if isinstance(obj, dict) and "__npy__" in obj:
        return np.load(io.BytesIO(base64.b64decode(obj["__npy__"])),
                       allow_pickle=False)
    return obj


class JsonWriter:
    """Append SampleBatches to JSONL shards (reference: json_writer.py)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._shard = 0
        self._f = None

    def _file(self):
        if self._f is None or self._f.tell() > self.max_file_size:
            if self._f:
                self._f.close()
            self._f = open(os.path.join(
                self.path, f"output-{self._shard:05d}.jsonl"), "a")
            self._shard += 1
        return self._f

    def write(self, batch: SampleBatch) -> None:
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        f = self._file()
        f.write(json.dumps(row) + "\n")
        f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


class JsonReader:
    """Stream SampleBatches back from JSONL shards
    (reference: json_reader.py). `next()` cycles forever, like the
    reference's bandit-style input readers."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "*.jsonl")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"no offline shards at {path!r}")
        self._iter = self._rows()

    def _rows(self) -> Iterator[SampleBatch]:
        while True:
            for fn in self.files:
                with open(fn) as f:
                    for line in f:
                        if not line.strip():
                            continue
                        row = json.loads(line)
                        yield SampleBatch(
                            {k: _decode(v) for k, v in row.items()})

    def next(self) -> SampleBatch:
        return next(self._iter)

    def read_all(self) -> SampleBatch:
        out: List[SampleBatch] = []
        for fn in self.files:
            with open(fn) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    out.append(SampleBatch(
                        {k: _decode(v) for k, v in row.items()}))
        return concat_samples(out)


# ---------------------------------------------------------------------------
# off-policy estimators (reference: rllib/offline/estimators/)
# ---------------------------------------------------------------------------

def _per_episode(batch: SampleBatch):
    """Episodes of a batch (SampleBatch.split_by_episode, which handles
    both EPS_ID boundaries and the DONES fallback)."""
    if not isinstance(batch, SampleBatch):
        batch = SampleBatch(batch)
    return batch.split_by_episode()


def importance_sampling(batch: SampleBatch, target_logp: np.ndarray,
                        gamma: float = 1.0) -> dict:
    """Ordinary IS estimate of the target policy's value from behaviour
    data (reference: estimators/importance_sampling.py). `target_logp` is
    the target policy's log-prob of the logged actions, aligned to batch
    rows; the behaviour log-prob comes from the logged ACTION_LOGP."""
    behaviour_logp = np.asarray(batch[sb.ACTION_LOGP])
    vals, raw = [], []
    offset = 0
    for ep in _per_episode(batch):
        t = len(ep[sb.REWARDS])
        lp_t = target_logp[offset:offset + t]
        lp_b = behaviour_logp[offset:offset + t]
        offset += t
        w = np.exp(np.cumsum(lp_t - lp_b))       # per-step products
        disc = gamma ** np.arange(t)
        vals.append(float(np.sum(w * disc * ep[sb.REWARDS])))
        raw.append(float(np.sum(disc * ep[sb.REWARDS])))
    return {"v_target": float(np.mean(vals)),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(np.mean(vals) / (np.mean(raw) + 1e-8))}


def weighted_importance_sampling(batch: SampleBatch,
                                 target_logp: np.ndarray,
                                 gamma: float = 1.0) -> dict:
    """WIS: weights normalized by the per-timestep mean weight across
    episodes (reference: estimators/weighted_importance_sampling.py) —
    biased but far lower variance than IS."""
    behaviour_logp = np.asarray(batch[sb.ACTION_LOGP])
    eps = []
    offset = 0
    for ep in _per_episode(batch):
        t = len(ep[sb.REWARDS])
        lp_t = target_logp[offset:offset + t]
        lp_b = behaviour_logp[offset:offset + t]
        offset += t
        eps.append((np.exp(np.cumsum(lp_t - lp_b)), ep[sb.REWARDS]))
    max_t = max(len(w) for w, _ in eps)
    # per-timestep normalizer over episodes still alive at t
    norm = np.zeros(max_t)
    cnt = np.zeros(max_t)
    for w, _ in eps:
        norm[:len(w)] += w
        cnt[:len(w)] += 1
    norm = norm / np.maximum(cnt, 1)
    vals, raw = [], []
    for w, r in eps:
        t = len(w)
        disc = gamma ** np.arange(t)
        vals.append(float(np.sum(w / (norm[:t] + 1e-8) * disc * r)))
        raw.append(float(np.sum(disc * r)))
    return {"v_target": float(np.mean(vals)),
            "v_behavior": float(np.mean(raw)),
            "v_gain": float(np.mean(vals) / (np.mean(raw) + 1e-8))}
