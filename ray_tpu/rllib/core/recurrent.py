"""Recurrent policy support: LSTM Q-module + stateful in-graph sampler.

The structural piece VERDICT r3 flagged missing: nothing in rollout.py
carried policy state. TPU-first design: the recurrent state is just
another pytree in the scan carry — the whole rollout (env vmap + LSTM
step + epsilon-greedy) stays one compiled `lax.scan`, and the sampler
emits fixed-length fragments WITH the state snapshot at fragment start.
That is exactly R2D2's "stored state" strategy (Kapturowski et al. 2019),
which the reference implements eagerly in
`rllib/algorithms/r2d2/r2d2.py` + `policy/rnn_sequencing.py`; here the
storage format falls out of the scan naturally.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.core.rl_module import build_torso
from ray_tpu.rllib.env.spaces import Box, Discrete


class _RecurrentQNet(nn.Module):
    """obs -> torso -> LSTMCell -> Q(a). Single-step; time handled by
    the caller's scan so rollout (step) and training (unroll) share the
    exact same cell."""
    num_actions: int
    obs_shape: tuple
    cfg: dict

    @nn.compact
    def __call__(self, obs, state):
        hidden = self.cfg.get("lstm_cell_size", 64)
        torso = build_torso(self.obs_shape, self.cfg, "relu", "torso")
        x = torso(obs)
        cell = nn.OptimizedLSTMCell(features=hidden)
        (c, h), out = cell((state[0], state[1]), x)
        q = nn.Dense(self.num_actions)(out)
        return q, (c, h)


class RecurrentQModule:
    """Q-network with LSTM state for R2D2-style algorithms.

    API mirrors QModule but every method threads `state` (a (c, h)
    tuple, both [B, hidden]):
      - initial_state(n)           -> zero state
      - q_step(params, obs, state) -> (q [B, A], state')
      - q_unroll(params, obs [T,B,...], dones [T,B], state0)
                                   -> (q [T,B,A], stateT)
        (state resets to zeros where done, so stored sequences may cross
        episode boundaries like the reference's rnn_sequencing)
      - compute_actions(params, obs, state, key, epsilon)
                                   -> (actions, q_sel, state')
    """

    def __init__(self, observation_space: Box, action_space: Discrete,
                 model_config: dict | None = None):
        if not isinstance(action_space, Discrete):
            raise ValueError(
                "RecurrentQModule requires a Discrete action space")
        cfg = dict(model_config or {})
        self.observation_space = observation_space
        self.action_space = action_space
        self.num_actions = action_space.n
        self.hidden = int(cfg.get("lstm_cell_size", 64))
        self._obs_shape = tuple(observation_space.shape)
        self.net = _RecurrentQNet(self.num_actions, self._obs_shape, cfg)

    def initial_state(self, n: int):
        return (jnp.zeros((n, self.hidden)), jnp.zeros((n, self.hidden)))

    def init(self, key) -> dict:
        dummy = jnp.zeros((1, *self._obs_shape))
        return self.net.init(key, dummy, self.initial_state(1))["params"]

    def q_step(self, params, obs, state):
        return self.net.apply({"params": params}, obs, state)

    def q_unroll(self, params, obs_seq, dones_seq, state0):
        def step(state, xs):
            obs, done = xs
            q, new_state = self.q_step(params, obs, state)
            # reset where the episode ended AFTER this step: the next
            # step's state must not leak across the boundary
            mask = (1.0 - done.astype(jnp.float32))[:, None]
            new_state = (new_state[0] * mask, new_state[1] * mask)
            return new_state, q
        stateT, q = jax.lax.scan(step, state0, (obs_seq, dones_seq))
        return q, stateT

    def compute_actions(self, params, obs, state, key, epsilon=0.0):
        q, new_state = self.q_step(params, obs, state)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(key)
        rand_actions = jax.random.randint(
            k1, greedy.shape, 0, self.num_actions)
        explore = jax.random.uniform(k2, greedy.shape) < epsilon
        actions = jnp.where(explore, rand_actions, greedy)
        q_sel = jnp.take_along_axis(q, actions[..., None], axis=-1)[..., 0]
        return actions, q_sel, new_state


class RecurrentInGraphSampler:
    """Compiled vectorized rollout that carries policy state and emits
    the fragment-start state alongside each fixed-length fragment —
    the sequence + stored-state format R2D2's replay wants, produced
    directly by the scan (no host-side rnn_sequencing pass)."""

    def __init__(self, env, module: RecurrentQModule, num_envs: int,
                 rollout_length: int):
        self.env = env
        self.module = module
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self._unroll = jax.jit(self._unroll_impl)

    def init_state(self, key):
        keys = jax.random.split(key, self.num_envs)
        state, obs = jax.vmap(self.env.reset)(keys)
        return {"env_state": state, "obs": obs,
                "policy_state": self.module.initial_state(self.num_envs),
                "ep_ret": jnp.zeros(self.num_envs),
                "ep_len": jnp.zeros(self.num_envs, jnp.int32)}

    def _unroll_impl(self, params, carry, key, epsilon):
        state0 = carry["policy_state"]

        def one_step(carry, step_key):
            k_act, k_env = jax.random.split(step_key)
            obs = carry["obs"]
            actions, q_sel, pol_state = self.module.compute_actions(
                params, obs, carry["policy_state"], k_act, epsilon)
            env_keys = jax.random.split(k_env, self.num_envs)
            state, next_obs, reward, done, _ = jax.vmap(self.env.step)(
                carry["env_state"], actions, env_keys)
            # zero the policy state where the episode ended — the auto-
            # reset env starts fresh, so must the memory
            mask = (1.0 - done.astype(jnp.float32))[:, None]
            pol_state = (pol_state[0] * mask, pol_state[1] * mask)
            ep_ret = carry["ep_ret"] + reward
            ep_len = carry["ep_len"] + 1
            finished_ret = jnp.where(done, ep_ret, jnp.nan)
            finished_len = jnp.where(done, ep_len, -1)
            new_carry = {
                "env_state": state,
                "obs": next_obs,
                "policy_state": pol_state,
                "ep_ret": jnp.where(done, 0.0, ep_ret),
                "ep_len": jnp.where(done, 0, ep_len),
            }
            out = {sb.OBS: obs, sb.ACTIONS: actions, sb.REWARDS: reward,
                   sb.DONES: done,
                   "episode_return": finished_ret,
                   "episode_len": finished_len}
            return new_carry, out

        step_keys = jax.random.split(key, self.rollout_length)
        carry, traj = jax.lax.scan(one_step, carry, step_keys)
        return carry, traj, state0

    def sample(self, params, carry, key, epsilon):
        """-> (new_carry, traj [T, num_envs, ...], fragment-start policy
        state (c, h) each [num_envs, hidden])."""
        return self._unroll(params, carry, key, epsilon)
