from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian

__all__ = ["RLModule", "Categorical", "DiagGaussian"]
