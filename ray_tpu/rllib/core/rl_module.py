"""RLModule — the neural network of an algorithm.

Counterpart of the reference's new-stack `RLModule`
(`rllib/core/rl_module/rl_module.py`) + the `ModelV2` catalog
(`rllib/models/catalog.py`): obs in → action-distribution inputs (+ value
estimate) out. Implemented as flax modules with explicit param pytrees so
the learner can shard/psum them like any other ray_tpu.train model.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian
from ray_tpu.rllib.env.spaces import Box, Discrete


_ACTIVATIONS = {"tanh": nn.tanh, "relu": nn.relu, "swish": nn.swish}


class _MLPTorso(nn.Module):
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"

    @nn.compact
    def __call__(self, x):
        act = _ACTIVATIONS[self.activation]
        for h in self.hiddens:
            x = act(nn.Dense(h)(x))
        return x


# Default conv stack for [H, W, C] observations: the classic DQN-paper
# architecture the reference catalog also defaults to for 84x84 inputs
# (rllib/models/catalog.py conv_filters). (out_channels, kernel, stride).
DEFAULT_CONV_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


def _patches(x, k: int, s: int):
    """SAME-padded kxk/stride-s patch extraction:
    [..., H, W, C] -> [..., Ho, Wo, k*k*C]. Written as pad + strided
    slices so the conv below becomes an explicit patch-matmul."""
    H, W = x.shape[-3], x.shape[-2]
    ho, wo = -(-H // s), -(-W // s)
    ph = max((ho - 1) * s + k - H, 0)
    pw = max((wo - 1) * s + k - W, 0)
    pad = [(0, 0)] * (x.ndim - 3) + [
        (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)]
    x = jnp.pad(x, pad)
    cols = []
    for di in range(k):
        for dj in range(k):
            cols.append(x[..., di:di + (ho - 1) * s + 1:s,
                          dj:dj + (wo - 1) * s + 1:s, :])
    return jnp.concatenate(cols, axis=-1)


class _ConvTorso(nn.Module):
    """NHWC conv encoder -> flat features, with each conv written as
    patch-extraction + matmul (a Dense over k*k*C patch columns). That
    is exactly how the MXU executes convs (implicit GEMM), so the
    compiled TPU program is identical-or-better than lax.conv — and the
    backward pass is matmul gradients, which avoids XLA:CPU's slow
    conv-transpose fallback on the CI/dev path. Channel counts are
    multiples of 8/16 so the MXU tiles the GEMMs cleanly."""
    filters: Tuple = DEFAULT_CONV_FILTERS
    hiddens: Tuple[int, ...] = (256,)
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        act = _ACTIVATIONS[self.activation]
        for i, (out, kernel, stride) in enumerate(self.filters):
            x = act(nn.Dense(out, name=f"Conv_{i}")(
                _patches(x, int(kernel), int(stride))))
        x = x.reshape(*x.shape[:-3], -1)
        for h in self.hiddens:
            x = act(nn.Dense(h)(x))
        return x


def build_torso(obs_shape: tuple, cfg: dict, default_activation: str,
                name: str):
    """Catalog seam (reference: `rllib/models/catalog.py` — pick the
    encoder from the observation space): rank-3 [H, W, C] observations
    get the conv stack, everything else the fcnet."""
    if len(obs_shape) == 3:
        return _ConvTorso(
            tuple(tuple(f) for f in cfg.get("conv_filters",
                                            DEFAULT_CONV_FILTERS)),
            tuple(cfg.get("post_fcnet_hiddens", (256,))),
            cfg.get("conv_activation", "relu"), name=name)
    return _MLPTorso(tuple(cfg.get("fcnet_hiddens", (64, 64))),
                     cfg.get("fcnet_activation", default_activation),
                     name=name)


class _PolicyValueNet(nn.Module):
    """Separate policy/value torsos (the reference's default fcnet with
    vf_share_layers=False, `rllib/models/catalog.py`); the torso kind
    comes from the catalog (conv for image obs)."""
    num_outputs: int
    obs_shape: tuple = ()
    model_config: dict = None
    activation: str = "tanh"

    @nn.compact
    def __call__(self, obs):
        cfg = self.model_config or {}
        pi = build_torso(self.obs_shape, cfg, self.activation, "pi")(obs)
        logits = nn.Dense(self.num_outputs, name="pi_out",
                          kernel_init=nn.initializers.orthogonal(0.01))(pi)
        vf = build_torso(self.obs_shape, cfg, self.activation, "vf")(obs)
        value = nn.Dense(1, name="vf_out")(vf)[..., 0]
        return logits, value


class _QNet(nn.Module):
    num_actions: int
    obs_shape: tuple = ()
    model_config: dict = None

    @nn.compact
    def __call__(self, obs):
        x = build_torso(self.obs_shape, self.model_config or {},
                        "relu", "q")(obs)
        return nn.Dense(self.num_actions)(x)


class RLModule:
    """Algorithm-agnostic policy network wrapper.

    Methods take explicit `params` (functional style) so the learner can
    jit/shard them; there is no hidden state, unlike ModelV2.
    """

    def __init__(self, observation_space: Box, action_space,
                 model_config: dict | None = None):
        cfg = dict(model_config or {})
        self.observation_space = observation_space
        self.action_space = action_space
        self.discrete = isinstance(action_space, Discrete)
        self.activation = cfg.get("fcnet_activation", "tanh")
        if self.discrete:
            self.num_outputs = action_space.n
        else:
            self.num_outputs = int(np.prod(action_space.shape)) * 2
        self._obs_shape = tuple(observation_space.shape)
        self.net = _PolicyValueNet(self.num_outputs, self._obs_shape,
                                   cfg, self.activation)

    def init(self, key) -> dict:
        dummy = jnp.zeros((1, *self._obs_shape))
        return self.net.init(key, dummy)["params"]

    def forward(self, params, obs):
        """-> (dist, value). Traceable."""
        out, value = self.net.apply({"params": params}, obs)
        return self.dist(out), value

    def dist(self, dist_inputs):
        if self.discrete:
            return Categorical(dist_inputs)
        mean, log_std = jnp.split(dist_inputs, 2, axis=-1)
        return DiagGaussian(mean, jnp.clip(log_std, -20.0, 2.0))

    def compute_actions(self, params, obs, key, explore: bool = True):
        """-> (actions, logp, value). Traceable; used by both rollout
        paths."""
        dist, value = self.forward(params, obs)
        actions = dist.sample(key) if explore else dist.deterministic()
        return actions, dist.logp(actions), value


class QModule:
    """Q-network for value-based algorithms (DQN family). Counterpart of
    the reference's DQN torso in `rllib/algorithms/dqn/dqn_torch_model.py`
    (without distributional/noisy extras)."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 model_config: dict | None = None):
        if not isinstance(action_space, Discrete):
            raise ValueError("QModule requires a Discrete action space")
        cfg = dict(model_config or {})
        self.observation_space = observation_space
        self.action_space = action_space
        self.num_actions = action_space.n
        self._obs_shape = tuple(observation_space.shape)
        self.net = _QNet(self.num_actions, self._obs_shape, cfg)

    def init(self, key) -> dict:
        dummy = jnp.zeros((1, *self._obs_shape))
        return self.net.init(key, dummy)["params"]

    def q_values(self, params, obs):
        return self.net.apply({"params": params}, obs)

    def compute_actions(self, params, obs, key, epsilon=0.0):
        """Epsilon-greedy. Traceable (epsilon may be a traced scalar).
        Returns (actions, q_selected, q_all) — logp slot repurposed."""
        q = self.q_values(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(key)
        rand_actions = jax.random.randint(
            k1, greedy.shape, 0, self.num_actions)
        explore_mask = jax.random.uniform(k2, greedy.shape) < epsilon
        actions = jnp.where(explore_mask, rand_actions, greedy)
        q_sel = jnp.take_along_axis(
            q, actions[..., None], axis=-1)[..., 0]
        return actions, q_sel, q
