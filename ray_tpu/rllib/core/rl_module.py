"""RLModule — the neural network of an algorithm.

Counterpart of the reference's new-stack `RLModule`
(`rllib/core/rl_module/rl_module.py`) + the `ModelV2` catalog
(`rllib/models/catalog.py`): obs in → action-distribution inputs (+ value
estimate) out. Implemented as flax modules with explicit param pytrees so
the learner can shard/psum them like any other ray_tpu.train model.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian
from ray_tpu.rllib.env.spaces import Box, Discrete


class _MLPTorso(nn.Module):
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"

    @nn.compact
    def __call__(self, x):
        act = {"tanh": nn.tanh, "relu": nn.relu,
               "swish": nn.swish}[self.activation]
        for h in self.hiddens:
            x = act(nn.Dense(h)(x))
        return x


class _PolicyValueNet(nn.Module):
    """Separate policy/value torsos (the reference's default fcnet with
    vf_share_layers=False, `rllib/models/catalog.py`)."""
    num_outputs: int
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"

    @nn.compact
    def __call__(self, obs):
        pi = _MLPTorso(self.hiddens, self.activation, name="pi")(obs)
        logits = nn.Dense(self.num_outputs, name="pi_out",
                          kernel_init=nn.initializers.orthogonal(0.01))(pi)
        vf = _MLPTorso(self.hiddens, self.activation, name="vf")(obs)
        value = nn.Dense(1, name="vf_out")(vf)[..., 0]
        return logits, value


class _QNet(nn.Module):
    num_actions: int
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "relu"

    @nn.compact
    def __call__(self, obs):
        x = _MLPTorso(self.hiddens, self.activation)(obs)
        return nn.Dense(self.num_actions)(x)


class RLModule:
    """Algorithm-agnostic policy network wrapper.

    Methods take explicit `params` (functional style) so the learner can
    jit/shard them; there is no hidden state, unlike ModelV2.
    """

    def __init__(self, observation_space: Box, action_space,
                 model_config: dict | None = None):
        cfg = dict(model_config or {})
        self.observation_space = observation_space
        self.action_space = action_space
        self.discrete = isinstance(action_space, Discrete)
        self.hiddens = tuple(cfg.get("fcnet_hiddens", (64, 64)))
        self.activation = cfg.get("fcnet_activation", "tanh")
        if self.discrete:
            self.num_outputs = action_space.n
        else:
            self.num_outputs = int(np.prod(action_space.shape)) * 2
        self.net = _PolicyValueNet(self.num_outputs, self.hiddens,
                                   self.activation)
        self._obs_dim = int(np.prod(observation_space.shape))

    def init(self, key) -> dict:
        dummy = jnp.zeros((1, self._obs_dim))
        return self.net.init(key, dummy)["params"]

    def forward(self, params, obs):
        """-> (dist, value). Traceable."""
        out, value = self.net.apply({"params": params}, obs)
        return self.dist(out), value

    def dist(self, dist_inputs):
        if self.discrete:
            return Categorical(dist_inputs)
        mean, log_std = jnp.split(dist_inputs, 2, axis=-1)
        return DiagGaussian(mean, jnp.clip(log_std, -20.0, 2.0))

    def compute_actions(self, params, obs, key, explore: bool = True):
        """-> (actions, logp, value). Traceable; used by both rollout
        paths."""
        dist, value = self.forward(params, obs)
        actions = dist.sample(key) if explore else dist.deterministic()
        return actions, dist.logp(actions), value


class QModule:
    """Q-network for value-based algorithms (DQN family). Counterpart of
    the reference's DQN torso in `rllib/algorithms/dqn/dqn_torch_model.py`
    (without distributional/noisy extras)."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 model_config: dict | None = None):
        if not isinstance(action_space, Discrete):
            raise ValueError("QModule requires a Discrete action space")
        cfg = dict(model_config or {})
        self.observation_space = observation_space
        self.action_space = action_space
        self.num_actions = action_space.n
        self.net = _QNet(self.num_actions,
                         tuple(cfg.get("fcnet_hiddens", (64, 64))),
                         cfg.get("fcnet_activation", "relu"))
        self._obs_dim = int(np.prod(observation_space.shape))

    def init(self, key) -> dict:
        dummy = jnp.zeros((1, self._obs_dim))
        return self.net.init(key, dummy)["params"]

    def q_values(self, params, obs):
        return self.net.apply({"params": params}, obs)

    def compute_actions(self, params, obs, key, epsilon=0.0):
        """Epsilon-greedy. Traceable (epsilon may be a traced scalar).
        Returns (actions, q_selected, q_all) — logp slot repurposed."""
        q = self.q_values(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(key)
        rand_actions = jax.random.randint(
            k1, greedy.shape, 0, self.num_actions)
        explore_mask = jax.random.uniform(k2, greedy.shape) < epsilon
        actions = jnp.where(explore_mask, rand_actions, greedy)
        q_sel = jnp.take_along_axis(
            q, actions[..., None], axis=-1)[..., 0]
        return actions, q_sel, q
