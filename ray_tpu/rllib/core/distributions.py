"""Action distributions (reference: `rllib/models/torch/torch_action_dist.py`
/ `rllib/models/distributions.py`) as stateless jnp functions — every method
is jit-traceable so sampling can live inside the compiled rollout."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    """Parameterized by logits [..., n]."""

    def __init__(self, logits):
        self.logits = logits

    def sample(self, key):
        return jax.random.categorical(key, self.logits, axis=-1)

    def logp(self, actions):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    def kl(self, other: "Categorical"):
        lp, lq = (jax.nn.log_softmax(self.logits, axis=-1),
                  jax.nn.log_softmax(other.logits, axis=-1))
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)

    def deterministic(self):
        return jnp.argmax(self.logits, axis=-1)


class DiagGaussian:
    """Parameterized by mean and log_std [..., act_dim]."""

    def __init__(self, mean, log_std):
        self.mean, self.log_std = mean, log_std

    def sample(self, key):
        eps = jax.random.normal(key, self.mean.shape)
        return self.mean + jnp.exp(self.log_std) * eps

    def logp(self, actions):
        var = jnp.exp(2 * self.log_std)
        ll = -0.5 * ((actions - self.mean) ** 2 / var
                     + 2 * self.log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    def entropy(self):
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e),
                       axis=-1)

    def kl(self, other: "DiagGaussian"):
        return jnp.sum(
            other.log_std - self.log_std
            + (jnp.exp(2 * self.log_std)
               + (self.mean - other.mean) ** 2)
            / (2 * jnp.exp(2 * other.log_std)) - 0.5, axis=-1)

    def deterministic(self):
        return self.mean
