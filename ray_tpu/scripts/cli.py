"""`python -m ray_tpu.scripts.cli` — the operator CLI.

Counterpart of the reference's `ray` CLI (`python/ray/scripts/scripts.py`):
`ray status` → status, `ray list tasks/actors/...` (state CLI,
`experimental/state/state_cli.py`) → list, `ray summary` → summary,
`ray timeline` → timeline, `ray job submit/status/logs/stop/list`
(`dashboard/modules/job/cli.py`) → job, `ray microbenchmark`
(`_private/ray_perf.py`) → microbenchmark. Attaches to the newest live
session's control socket (or --session DIR).
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
import time


def _resolve_session(args) -> str:
    """--session, else RAY_TPU_ADDRESS (what `ray_tpu attach` exports
    into its subshell), else the newest live session on this host (exit
    1 if none)."""
    import os
    from ray_tpu._private.attach import find_sessions
    session = args.session or os.environ.get("RAY_TPU_ADDRESS")
    if session is None:
        sessions = find_sessions()
        if not sessions:
            print("no live ray_tpu session found", file=sys.stderr)
            sys.exit(1)
        session = sessions[0]
    return session


def _attach(args):
    from ray_tpu._private.attach import AttachClient
    return AttachClient(_resolve_session(args))


def _print(obj):
    print(json.dumps(obj, indent=2, default=str))


def cmd_status(args):
    c = _attach(args)
    nodes = c.control("list_nodes")
    workers = c.control("list_workers")
    print(f"session: {c.session_dir}")
    for n in nodes:
        total, avail = n["resources_total"], n["resources_available"]
        usage = ", ".join(
            f"{total[k] - avail.get(k, 0):g}/{total[k]:g} {k}"
            for k in sorted(total))
        print(f"node {n['node_id']}: {usage}")
    alive = sum(1 for w in workers if w["alive"])
    print(f"workers: {alive} alive / {len(workers)} total")
    a = c.control("autoscaler_status")
    if a.get("enabled"):
        print(f"autoscaler: {sum(a['workers_by_type'].values())}/"
              f"{a['max_workers']} workers "
              f"({', '.join(f'{k}: {v}' for k, v in sorted(a['workers_by_type'].items())) or 'none'}); "
              f"pending demands: {a['pending_demands']}, "
              f"pending gangs: {a['pending_gangs']}, "
              f"infeasible: {a['infeasible_gangs']}"
              + (f"; last error: {a['last_error']}"
                 if a.get("last_error") else ""))


def cmd_list(args):
    c = _attach(args)
    method = {
        "tasks": "list_tasks", "actors": "list_actors",
        "workers": "list_workers", "objects": "list_objects",
        "nodes": "list_nodes",
        "placement-groups": "list_placement_groups",
    }[args.kind]
    _print(c.control(method))


def cmd_summary(args):
    _print(_attach(args).control("summarize_tasks"))


def cmd_timeline(args):
    payload = {"trace": args.trace} if getattr(args, "trace", None) else None
    events = _attach(args).control("timeline", payload)
    with open(args.output, "w") as f:
        json.dump(events, f)
    # The merged view carries task events, engine request spans, and
    # application tracing spans — break the count down by category so a
    # dump with zero request spans (telemetry sampled off?) is obvious.
    cats = collections.Counter(e.get("cat", "?") for e in events)
    by_cat = ", ".join(f"{k}={v}" for k, v in sorted(cats.items()))
    print(f"wrote {len(events)} events ({by_cat or 'empty'}) to "
          f"{args.output} (open in chrome://tracing or ui.perfetto.dev)")


def cmd_stack(args):
    """`ray_tpu stack [WORKER_ID]` — every worker's Python stacks
    (reference: `ray stack`, scripts.py:1786)."""
    c = _attach(args)
    dumps = c.control("stack", {"worker_id": args.worker_id,
                                "timeout": args.timeout},
                      timeout=args.timeout + 30)
    if not dumps:
        print("no stacks collected (no matching live workers?)")
        return
    for wid, d in sorted(dumps.items()):
        print(f"===== {wid} (pid {d['pid']}) =====")
        print(d["stacks"])
        print()


def cmd_logs(args):
    """`ray_tpu logs [SOURCE]` — list log sources or tail one
    (reference: `ray logs`, dashboard log module)."""
    c = _attach(args)
    if args.source is None:
        for row in c.control("list_logs"):
            print(f"{row['source']}\t{row['lines']} lines")
    else:
        for ln in c.control("get_log", {"source": args.source,
                                        "lines": args.lines}):
            print(ln)


def cmd_metrics(args):
    from ray_tpu.util.metrics import render_prometheus
    print(render_prometheus(_attach(args).control("get_metrics")), end="")


def cmd_job(args):
    c = _attach(args)
    if args.job_cmd == "submit":
        job_id = c.control("job_submit", {
            "entrypoint": " ".join(args.entrypoint),
            "job_id": args.job_id, "runtime_env": None, "metadata": None})
        print(job_id)
        if args.wait:
            while True:
                st = c.control("job_status", job_id)["status"]
                if st in ("SUCCEEDED", "FAILED", "STOPPED"):
                    print(st)
                    print(c.control("job_logs", job_id), end="")
                    sys.exit(0 if st == "SUCCEEDED" else 1)
                time.sleep(0.5)
    elif args.job_cmd == "status":
        _print(c.control("job_status", args.job_id))
    elif args.job_cmd == "logs":
        print(c.control("job_logs", args.job_id), end="")
    elif args.job_cmd == "stop":
        print(c.control("job_stop", args.job_id))
    elif args.job_cmd == "list":
        _print(c.control("job_list"))


def cmd_start(args):
    """`ray_tpu start --head` / `ray_tpu start --address host:port` —
    cluster lifecycle (reference: scripts.py:537 `ray start`). The head
    runs as its OWN process (gcs_server binary counterpart); additional
    machines join by running a HostDaemon against the head's TCP
    address."""
    import os
    import subprocess
    import time as _time

    if args.head:
        cmd = [sys.executable, "-m", "ray_tpu._private.head_main"]
        if args.port is not None:
            cmd += ["--port", str(args.port)]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if args.resources:
            cmd += ["--resources", args.resources]
        if args.session_dir:
            cmd += ["--session-dir", args.session_dir]
        if args.block:
            os.execv(sys.executable, cmd)
        import select
        env = dict(os.environ)
        env["RAY_TPU_HEAD_DETACHED"] = "1"   # head logs to session dir
        proc = subprocess.Popen(cmd, start_new_session=True, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        # relay startup lines until the head reports ready; the deadline
        # must hold even if the head prints nothing (select, not a
        # blocking readline)
        deadline = _time.time() + 60
        session = None
        ready = False
        while not ready:
            rem = deadline - _time.time()
            if rem <= 0:
                print("head startup timed out", file=sys.stderr)
                proc.kill()
                sys.exit(1)
            r, _, _ = select.select([proc.stdout], [], [], min(rem, 1.0))
            if not r:
                continue
            line = proc.stdout.readline()
            if not line:          # EOF: the head died before readiness
                print("head failed to start", file=sys.stderr)
                sys.exit(1)
            print(line, end="")   # relay the WHOLE banner (address/join)
            if line.startswith("ray_tpu head up: session="):
                session = line.split("session=", 1)[1].strip()
            ready = line.startswith("drive:")
        # returned so callers (cmd_up) know EXACTLY which session this
        # head owns instead of guessing by mtime
        return session

    if not args.address:
        print("start needs --head or --address HOST:PORT", file=sys.stderr)
        sys.exit(1)
    key = args.authkey or os.environ.get("RAY_TPU_AUTHKEY")
    if not key:
        print("joining a head needs the session authkey: --authkey HEX "
              "or RAY_TPU_AUTHKEY", file=sys.stderr)
        sys.exit(1)
    import ray_tpu
    from ray_tpu._private import ids, spawn
    num_cpus = args.num_cpus if args.num_cpus is not None \
        else (os.cpu_count() or 1)
    num_tpus = args.num_tpus if args.num_tpus is not None \
        else ray_tpu._detect_tpu_chips()
    res = {"CPU": float(num_cpus)}
    if num_tpus:
        res["TPU"] = float(num_tpus)
    for k, v in json.loads(args.resources or "{}").items():
        res[str(k)] = float(v)
    node_id = ids.new_node_id()
    env = spawn.propagate_pythonpath(dict(os.environ))
    env["RAY_TPU_AUTHKEY"] = key
    cmd = [sys.executable, "-m", "ray_tpu._private.daemon",
           args.address, node_id, json.dumps(res), str(int(num_tpus or 0))]
    if args.block:
        os.environ["RAY_TPU_AUTHKEY"] = key
        os.execve(sys.executable, cmd, env)
    proc = subprocess.Popen(cmd, env=env, start_new_session=True)
    print(f"node {node_id} joining {args.address} (pid {proc.pid})")


def _cluster_state_path(name: str) -> str:
    import os
    root = os.environ.get("RAY_TPU_CLUSTER_STATE_DIR") or \
        os.path.expanduser("~/.ray_tpu/clusters")
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, f"{name}.json")


def cmd_up(args):
    """`ray_tpu up -f cluster.yaml` — bring a cluster up from a config
    (reference: the cluster launcher, scripts.py:1235 `ray up` +
    autoscaler/_private/commands.py): start a standalone head, attach
    the autoscaler with the config's node types, and let min_workers
    populate. Provider: LocalDaemonNodeProvider (one machine); remote
    machines join with `ray_tpu start --address`."""
    import os
    import time as _time

    import yaml

    with open(args.file) as f:
        cfg = yaml.safe_load(f)
    name = cfg.get("cluster_name", "default")
    if os.path.exists(_cluster_state_path(name)):
        print(f"cluster {name!r} already has state "
              f"({_cluster_state_path(name)}); run `ray_tpu down {name}` "
              "first", file=sys.stderr)
        sys.exit(1)
    head_cfg = cfg.get("head", {})
    cloud_provider = (cfg.get("provider") or {}).get("type", "local") \
        not in ("local",)
    head_port = head_cfg.get("port")
    if cloud_provider and head_port is None:
        # slices join over TCP; an ephemeral bind is fine because the
        # startup scripts embed the actual bound address
        head_port = 0

    # start the head detached (same path as `start --head`)
    head_args = argparse.Namespace(
        head=True, address=None, authkey=None,
        port=head_port, num_cpus=head_cfg.get("num_cpus"),
        num_tpus=head_cfg.get("num_tpus"),
        resources=json.dumps(head_cfg.get("resources", {})),
        session_dir=None, block=False)
    session = cmd_start(head_args)
    if not session:
        print("could not determine the new head's session", file=sys.stderr)
        sys.exit(1)
    from ray_tpu._private.attach import AttachClient
    c = AttachClient(session)
    provider_cfg = dict(cfg.get("provider") or {"type": "local"})
    provider_cfg.setdefault("cluster_name", name)
    autoscaler_cfg = {
        "max_workers": cfg.get("max_workers", 8),
        "idle_timeout_minutes": cfg.get("idle_timeout_minutes", 5.0),
        "available_node_types": cfg.get("available_node_types", {}),
        "provider": provider_cfg,
    }
    # node_config always carries the declared resources: local providers
    # spawn daemons with them, the gcp-tpu provider forwards the custom
    # ones through the slice startup script
    for spec in autoscaler_cfg["available_node_types"].values():
        spec.setdefault("node_config", {})
        spec["node_config"].setdefault(
            "resources", spec.get("resources", {}))
    c.control("attach_autoscaler", autoscaler_cfg)

    with open(_cluster_state_path(name), "w") as f:
        json.dump({"session": session, "config_file":
                   os.path.abspath(args.file)}, f)

    # wait for min_workers to come up. Local providers become cluster
    # nodes directly; cloud providers (gcp-tpu) report provisioned
    # slices through the autoscaler while their hosts boot and join, so
    # the readiness signal is provider-side there.
    want = sum(s.get("min_workers", 0)
               for s in autoscaler_cfg["available_node_types"].values())
    cloud = provider_cfg.get("type", "local") not in ("local",)
    deadline = _time.time() + 120
    n_up = 0
    while _time.time() < deadline:
        if cloud:
            st = c.control("autoscaler_status")
            n_up = sum((st.get("workers_by_type") or {}).values())
        else:
            n_up = len([n for n in c.control("list_nodes")
                        if n["alive"] and not n.get("head")])
        if n_up >= want:
            break
        _time.sleep(1.0)
    c.close()
    if n_up < want:
        print(f"cluster {name!r}: only {n_up}/{want} min_workers "
              f"came up within 120s", file=sys.stderr)
        sys.exit(1)
    print(f"cluster {name!r} up: session={session}, "
          f"{n_up} worker {'slice' if cloud else 'node'}(s)")


def _cluster_session(args) -> str:
    import os
    if getattr(args, "session", None):
        return args.session
    path = _cluster_state_path(args.name)
    if not os.path.exists(path):
        print(f"no cluster state for {args.name!r} (ran `up`?)",
              file=sys.stderr)
        sys.exit(1)
    with open(path) as f:
        return json.load(f)["session"]


def cmd_down(args):
    """`ray_tpu down NAME` — tear the cluster down (reference: `ray
    down`, scripts.py:1235+)."""
    import os
    import signal as _signal
    session = _cluster_session(args)
    # terminate provider nodes FIRST: with a cloud provider (gcp-tpu)
    # these are billed TPU slices that nothing else remembers once the
    # state file is gone
    try:
        from ray_tpu._private.attach import AttachClient
        c = AttachClient(session)
        res = c.control("autoscaler_teardown")
        c.close()
        if res.get("terminated"):
            print(f"terminated {res['terminated']} provider node(s)")
        for e in res.get("errors") or []:
            print(f"terminate failed: {e}", file=sys.stderr)
    except Exception:
        pass    # no autoscaler / head already gone
    try:
        with open(os.path.join(session, "driver.pid")) as f:
            pid = int(f.read().strip())
        os.kill(pid, _signal.SIGTERM)
        print(f"cluster {args.name!r} down (head pid {pid})")
    except (OSError, ValueError) as e:
        # head already gone (crash/reboot): still clear the state so
        # the cluster name isn't wedged forever
        print(f"head already gone ({e}); clearing cluster state")
    try:
        os.unlink(_cluster_state_path(args.name))
    except OSError:
        pass


def cmd_attach(args):
    """`ray_tpu attach NAME` — a subshell wired to the cluster
    (reference: `ray attach` opens a shell on the head; locally that
    means RAY_TPU_ADDRESS/AUTHKEY exported so `ray_tpu.init(address=
    os.environ['RAY_TPU_ADDRESS'])` and the CLI hit this cluster)."""
    import os
    session = _cluster_session(args)
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = session
    with open(os.path.join(session, "authkey"), "rb") as f:
        env["RAY_TPU_AUTHKEY"] = f.read().hex()
    shell = env.get("SHELL", "/bin/sh")
    print(f"attached to {session} (exit the shell to detach)")
    os.execve(shell, [shell], env)


def cmd_submit(args):
    """`ray_tpu submit NAME script.py [args...]` — run a script as a job
    on the cluster and stream its result (reference: `ray submit`,
    scripts.py:1235-1728)."""
    import os
    session = _cluster_session(args)
    from ray_tpu._private.attach import AttachClient
    c = AttachClient(session)
    import shlex
    entry = " ".join(shlex.quote(p) for p in
                     [sys.executable, os.path.abspath(args.script),
                      *args.script_args])
    job_id = c.control("job_submit", {
        "entrypoint": entry, "job_id": None,
        "runtime_env": {"env_vars": {"RAY_TPU_ADDRESS": session}},
        "metadata": None})
    print(f"submitted {job_id}")
    while True:
        st = c.control("job_status", job_id)["status"]
        if st in ("SUCCEEDED", "FAILED", "STOPPED"):
            print(c.control("job_logs", job_id), end="")
            print(st)
            sys.exit(0 if st == "SUCCEEDED" else 1)
        time.sleep(0.5)


def cmd_stop(args):
    """`ray_tpu stop`: SIGTERM the head(s) of live sessions on this host
    (reference: scripts.py:1001 `ray stop`). Daemons die when their head
    channel closes (unless a restart follows within the reconnect
    grace)."""
    import os
    import signal as _signal
    from ray_tpu._private.attach import find_sessions
    sessions = [args.session] if args.session else find_sessions()
    if not sessions:
        print("no live ray_tpu session found")
        return
    for d in sessions:
        try:
            with open(os.path.join(d, "driver.pid")) as f:
                pid = int(f.read().strip())
            os.kill(pid, _signal.SIGTERM)
            print(f"stopped head of {d} (pid {pid})")
        except (OSError, ValueError) as e:
            print(f"could not stop {d}: {e}", file=sys.stderr)


def cmd_serve(args):
    """`ray_tpu serve apply -f config.yaml` / `ray_tpu serve status` —
    the declarative deploy path (reference: `serve deploy`/`serve
    status` CLIs over serve/schema.py). Runs in-process as a client
    driver of the target session."""
    import ray_tpu
    ray_tpu.init(address=_resolve_session(args))
    from ray_tpu import serve
    if args.serve_cmd == "apply":
        _print(serve.apply_config(args.file))
    elif args.serve_cmd == "status":
        _print(serve.status())


def cmd_config(args):
    """`ray_tpu config list`: print the typed option table with effective
    values (reference: the RAY_CONFIG table, ray_config_def.h)."""
    from ray_tpu._private import constants  # noqa: F401  (registers opts)
    from ray_tpu._private.config import describe
    rows = describe()
    if getattr(args, "json", False):
        import json
        print(json.dumps(rows, indent=2))
        return
    width = max(len(r["env"]) for r in rows)
    for r in rows:
        mark = "*" if r["overridden"] else " "
        print(f"{mark} {r['env']:<{width}}  {r['type']:<6} "
              f"current={r['current']!r} default={r['default']!r}")
        print(f"  {' ' * width}  {r['doc']}")


def cmd_microbenchmark(args):
    """Core-runtime throughput suite (reference: ray_perf.py:93)."""
    import ray_tpu
    import numpy as np
    ray_tpu.init(num_cpus=args.num_cpus)

    @ray_tpu.remote
    def nop():
        return None

    # warm the worker pool
    ray_tpu.get([nop.remote() for _ in range(args.num_cpus)])

    t0 = time.time()
    n = 200
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.time() - t0
    print(f"tasks_per_second: {n / dt:.1f}")

    t0 = time.time()
    n = 200
    arr = np.zeros(1024, np.float32)      # small put/get
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(arr))
    dt = time.time() - t0
    print(f"small_put_get_per_second: {n / dt:.1f}")

    big = np.zeros(25_000_000 // 4, np.float32)   # 25 MB through the arena
    t0 = time.time()
    n = 40
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(big))
    dt = time.time() - t0
    print(f"object_store_GBps: {n * big.nbytes / dt / 1e9:.2f}")

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.i = 0

        def inc(self):
            self.i += 1
            return self.i

    a = Counter.remote()
    ray_tpu.get(a.inc.remote())
    t0 = time.time()
    n = 200
    ray_tpu.get([a.inc.remote() for _ in range(n)])
    dt = time.time() - t0
    print(f"actor_calls_per_second: {n / dt:.1f}")
    ray_tpu.shutdown()


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--session", default=None,
                   help="session dir (default: newest live session)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None,
                    help="head HOST:PORT to join as a worker node")
    sp.add_argument("--authkey", default=None,
                    help="session authkey hex (or RAY_TPU_AUTHKEY)")
    sp.add_argument("--port", type=int, default=None,
                    help="head TCP port (enables cross-machine joins)")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.add_argument("--resources", default=None)
    sp.add_argument("--session-dir", default=None)
    sp.add_argument("--block", action="store_true",
                    help="run in the foreground")
    sp.set_defaults(fn=cmd_start)

    st = sub.add_parser("stop")
    st.set_defaults(fn=cmd_stop)

    up = sub.add_parser("up")
    up.add_argument("-f", "--file", required=True)
    up.set_defaults(fn=cmd_up)

    dn = sub.add_parser("down")
    dn.add_argument("name", nargs="?", default="default")
    dn.set_defaults(fn=cmd_down)

    at = sub.add_parser("attach")
    at.add_argument("name", nargs="?", default="default")
    at.set_defaults(fn=cmd_attach)

    sm = sub.add_parser("submit")
    sm.add_argument("name")
    sm.add_argument("script")
    sm.add_argument("script_args", nargs=argparse.REMAINDER)
    sm.set_defaults(fn=cmd_submit)

    sub.add_parser("status").set_defaults(fn=cmd_status)

    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["tasks", "actors", "workers", "objects",
                                     "nodes", "placement-groups"])
    lp.set_defaults(fn=cmd_list)

    sub.add_parser("summary").set_defaults(fn=cmd_summary)

    tp = sub.add_parser("timeline")
    tp.add_argument("output", nargs="?", default="timeline.json")
    tp.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="only events of one distributed trace")
    tp.set_defaults(fn=cmd_timeline)

    sub.add_parser("metrics").set_defaults(fn=cmd_metrics)

    stk = sub.add_parser("stack")
    stk.add_argument("worker_id", nargs="?", default=None)
    stk.add_argument("--timeout", type=float, default=5.0)
    stk.set_defaults(fn=cmd_stack)

    lg = sub.add_parser("logs")
    lg.add_argument("source", nargs="?", default=None)
    lg.add_argument("--lines", type=int, default=200)
    lg.set_defaults(fn=cmd_logs)

    jp = sub.add_parser("job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--job-id", default=None)
    js.add_argument("--wait", action="store_true")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        jx = jsub.add_parser(name)
        jx.add_argument("job_id")
    jsub.add_parser("list")
    jp.set_defaults(fn=cmd_job)

    sv = sub.add_parser("serve")
    svsub = sv.add_subparsers(dest="serve_cmd", required=True)
    sva = svsub.add_parser("apply")
    sva.add_argument("-f", "--file", required=True)
    svsub.add_parser("status")
    sv.set_defaults(fn=cmd_serve)

    mb = sub.add_parser("microbenchmark")
    mb.add_argument("--num-cpus", type=int, default=4)
    mb.set_defaults(fn=cmd_microbenchmark)

    cp = sub.add_parser("config")
    cp.add_argument("config_cmd", choices=["list"])
    cp.add_argument("--json", action="store_true")
    cp.set_defaults(fn=cmd_config)

    args = p.parse_args(argv)
    try:
        args.fn(args)
    except BrokenPipeError:
        # downstream pager/grep closed the pipe; standard CLI etiquette
        try:
            sys.stdout.close()
        except OSError:
            pass
        sys.exit(0)


if __name__ == "__main__":
    main()
