"""`python -m ray_tpu.scripts.cli` — the operator CLI.

Counterpart of the reference's `ray` CLI (`python/ray/scripts/scripts.py`):
`ray status` → status, `ray list tasks/actors/...` (state CLI,
`experimental/state/state_cli.py`) → list, `ray summary` → summary,
`ray timeline` → timeline, `ray job submit/status/logs/stop/list`
(`dashboard/modules/job/cli.py`) → job, `ray microbenchmark`
(`_private/ray_perf.py`) → microbenchmark. Attaches to the newest live
session's control socket (or --session DIR).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _attach(args):
    from ray_tpu._private.attach import AttachClient, find_sessions
    session = args.session
    if session is None:
        sessions = find_sessions()
        if not sessions:
            print("no live ray_tpu session found", file=sys.stderr)
            sys.exit(1)
        session = sessions[0]
    return AttachClient(session)


def _print(obj):
    print(json.dumps(obj, indent=2, default=str))


def cmd_status(args):
    c = _attach(args)
    nodes = c.control("list_nodes")
    workers = c.control("list_workers")
    print(f"session: {c.session_dir}")
    for n in nodes:
        total, avail = n["resources_total"], n["resources_available"]
        usage = ", ".join(
            f"{total[k] - avail.get(k, 0):g}/{total[k]:g} {k}"
            for k in sorted(total))
        print(f"node {n['node_id']}: {usage}")
    alive = sum(1 for w in workers if w["alive"])
    print(f"workers: {alive} alive / {len(workers)} total")


def cmd_list(args):
    c = _attach(args)
    method = {
        "tasks": "list_tasks", "actors": "list_actors",
        "workers": "list_workers", "objects": "list_objects",
        "nodes": "list_nodes",
        "placement-groups": "list_placement_groups",
    }[args.kind]
    _print(c.control(method))


def cmd_summary(args):
    _print(_attach(args).control("summarize_tasks"))


def cmd_timeline(args):
    events = _attach(args).control("timeline")
    with open(args.output, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {args.output} "
          "(open in chrome://tracing or ui.perfetto.dev)")


def cmd_metrics(args):
    from ray_tpu.util.metrics import render_prometheus
    print(render_prometheus(_attach(args).control("get_metrics")), end="")


def cmd_job(args):
    c = _attach(args)
    if args.job_cmd == "submit":
        job_id = c.control("job_submit", {
            "entrypoint": " ".join(args.entrypoint),
            "job_id": args.job_id, "runtime_env": None, "metadata": None})
        print(job_id)
        if args.wait:
            while True:
                st = c.control("job_status", job_id)["status"]
                if st in ("SUCCEEDED", "FAILED", "STOPPED"):
                    print(st)
                    print(c.control("job_logs", job_id), end="")
                    sys.exit(0 if st == "SUCCEEDED" else 1)
                time.sleep(0.5)
    elif args.job_cmd == "status":
        _print(c.control("job_status", args.job_id))
    elif args.job_cmd == "logs":
        print(c.control("job_logs", args.job_id), end="")
    elif args.job_cmd == "stop":
        print(c.control("job_stop", args.job_id))
    elif args.job_cmd == "list":
        _print(c.control("job_list"))


def cmd_config(args):
    """`ray_tpu config list`: print the typed option table with effective
    values (reference: the RAY_CONFIG table, ray_config_def.h)."""
    from ray_tpu._private import constants  # noqa: F401  (registers opts)
    from ray_tpu._private.config import describe
    rows = describe()
    if getattr(args, "json", False):
        import json
        print(json.dumps(rows, indent=2))
        return
    width = max(len(r["env"]) for r in rows)
    for r in rows:
        mark = "*" if r["overridden"] else " "
        print(f"{mark} {r['env']:<{width}}  {r['type']:<6} "
              f"current={r['current']!r} default={r['default']!r}")
        print(f"  {' ' * width}  {r['doc']}")


def cmd_microbenchmark(args):
    """Core-runtime throughput suite (reference: ray_perf.py:93)."""
    import ray_tpu
    import numpy as np
    ray_tpu.init(num_cpus=args.num_cpus)

    @ray_tpu.remote
    def nop():
        return None

    # warm the worker pool
    ray_tpu.get([nop.remote() for _ in range(args.num_cpus)])

    t0 = time.time()
    n = 200
    ray_tpu.get([nop.remote() for _ in range(n)])
    dt = time.time() - t0
    print(f"tasks_per_second: {n / dt:.1f}")

    t0 = time.time()
    n = 200
    arr = np.zeros(1024, np.float32)      # small put/get
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(arr))
    dt = time.time() - t0
    print(f"small_put_get_per_second: {n / dt:.1f}")

    big = np.zeros(25_000_000 // 4, np.float32)   # 25 MB through the arena
    t0 = time.time()
    n = 40
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(big))
    dt = time.time() - t0
    print(f"object_store_GBps: {n * big.nbytes / dt / 1e9:.2f}")

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.i = 0

        def inc(self):
            self.i += 1
            return self.i

    a = Counter.remote()
    ray_tpu.get(a.inc.remote())
    t0 = time.time()
    n = 200
    ray_tpu.get([a.inc.remote() for _ in range(n)])
    dt = time.time() - t0
    print(f"actor_calls_per_second: {n / dt:.1f}")
    ray_tpu.shutdown()


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--session", default=None,
                   help="session dir (default: newest live session)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status").set_defaults(fn=cmd_status)

    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["tasks", "actors", "workers", "objects",
                                     "nodes", "placement-groups"])
    lp.set_defaults(fn=cmd_list)

    sub.add_parser("summary").set_defaults(fn=cmd_summary)

    tp = sub.add_parser("timeline")
    tp.add_argument("output", nargs="?", default="timeline.json")
    tp.set_defaults(fn=cmd_timeline)

    sub.add_parser("metrics").set_defaults(fn=cmd_metrics)

    jp = sub.add_parser("job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--job-id", default=None)
    js.add_argument("--wait", action="store_true")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        jx = jsub.add_parser(name)
        jx.add_argument("job_id")
    jsub.add_parser("list")
    jp.set_defaults(fn=cmd_job)

    mb = sub.add_parser("microbenchmark")
    mb.add_argument("--num-cpus", type=int, default=4)
    mb.set_defaults(fn=cmd_microbenchmark)

    cp = sub.add_parser("config")
    cp.add_argument("config_cmd", choices=["list"])
    cp.add_argument("--json", action="store_true")
    cp.set_defaults(fn=cmd_config)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
