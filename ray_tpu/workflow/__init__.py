"""Durable workflows: DAGs whose step results persist across failures.

Counterpart of the reference's `python/ray/workflow/` (10k LoC):
`workflow_executor.py:32` drives a state machine over the DAG,
`workflow_storage.py:229` persists every step result so a crashed or
killed run resumes from the last completed step, `api.py` exposes
run/resume/list/get_output. Here the executor walks the `ray_tpu.dag`
expression tree; each FunctionNode becomes a durable *step* whose result
is checkpointed to storage (filesystem dir, one file per step) before the
next step may consume it. Step identity is CONTENT-BASED (function code
hash + upstream step ids + static args), so editing a DAG invalidates
exactly the edited step and its downstream on resume instead of silently
re-binding old results to new code — stricter than the reference's
name-indexed steps (`workflow_storage.py:229`).

Dynamic workflows (reference: `workflow_executor.py:32` continuations):
a step may RETURN a DAG; the executor runs the returned sub-DAG durably
in a namespaced step scope and the sub-DAG's result becomes the step's
result — recursive workflows checkpoint at every level.

Limitations vs reference (documented, not hidden): no virtual actors
(deprecated upstream), no cross-workflow events; ClassNode/actor steps
execute but are not checkpointed (actors are stateful; the reference
workflow layer likewise only checkpoints function steps).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

_storage_root: Optional[str] = None


def init(storage: str | None = None) -> None:
    """Set the durable storage root (default: RAY_TPU_WORKFLOW_DIR or
    ~/.ray_tpu/workflows)."""
    global _storage_root
    _storage_root = storage or os.environ.get(
        "RAY_TPU_WORKFLOW_DIR",
        os.path.expanduser("~/.ray_tpu/workflows"))
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    if _storage_root is None:
        init()
    return _storage_root


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_root(), workflow_id)


# ---------------------------------------------------------------------------
# storage (reference: workflow_storage.py)
# ---------------------------------------------------------------------------

class _Storage:
    def __init__(self, workflow_id: str):
        self.dir = _wf_dir(workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    def save_meta(self, meta: dict):
        tmp = os.path.join(self.dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    def load_meta(self) -> dict | None:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def save_dag(self, dag: DAGNode, dag_input):
        import cloudpickle
        tmp = os.path.join(self.dir, "dag.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump((dag, dag_input), f)
        os.replace(tmp, os.path.join(self.dir, "dag.pkl"))

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, step_id + ".pkl")

    # a step that returned a DAG checkpoints the RETURNED DAG before the
    # continuation runs, so a crash mid-continuation resumes without
    # re-executing the parent step (reference: dynamic workflow progress,
    # workflow_storage.py save_workflow_execution_state)
    def cont_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, step_id + ".cont.pkl")

    def has_continuation(self, step_id: str) -> bool:
        return os.path.exists(self.cont_path(step_id))

    def save_continuation(self, step_id: str, subdag) -> None:
        import cloudpickle
        tmp = self.cont_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(subdag, f)
        os.replace(tmp, self.cont_path(step_id))

    def load_continuation(self, step_id: str):
        with open(self.cont_path(step_id), "rb") as f:
            return pickle.load(f)

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))   # atomic commit

    def load_step(self, step_id: str):
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# executor (reference: workflow_executor.py run_until_complete :72)
# ---------------------------------------------------------------------------

def _topo_order(dag: DAGNode) -> list[DAGNode]:
    """Children-first deterministic topological order (shared nodes once)."""
    seen: Dict[int, bool] = {}
    order: list[DAGNode] = []

    def visit(node: DAGNode):
        if id(node) in seen:
            return
        seen[id(node)] = True
        for child in node._children():
            visit(child)
        order.append(node)
    visit(dag)
    return order


def _code_hash(fn) -> str:
    import hashlib
    code = getattr(fn, "__code__", None)
    if code is not None:
        payload = code.co_code + repr(code.co_consts).encode()
    else:
        payload = repr(fn).encode()
    return hashlib.sha1(payload).hexdigest()[:10]


def _static_repr(value) -> str:
    """Stable digest for non-node step arguments."""
    import hashlib

    import cloudpickle
    try:
        return hashlib.sha1(cloudpickle.dumps(value)).hexdigest()[:10]
    except Exception:
        return "opaque"


def _step_ids(nodes: list[DAGNode]) -> Dict[int, str]:
    """CONTENT-BASED step id per FunctionNode: function name + a hash of
    (function bytecode, upstream step ids, static args). Editing a step's
    code or its inputs changes its id (and its downstream's), so resume
    re-executes exactly the affected subgraph instead of silently
    re-binding a stale checkpoint — the failure mode of positional ids.
    Identical-content siblings are disambiguated by a deterministic
    occurrence index."""
    import hashlib
    ids: Dict[int, str] = {}        # id(node) -> step id
    content: Dict[int, str] = {}    # id(node) -> content token (any node)
    seen_count: Dict[str, int] = {}
    for node in nodes:              # children-first topological order
        child_tokens = [content[id(c)] for c in node._children()]
        if not isinstance(node, FunctionNode):
            # discriminating payload of non-function nodes must ride the
            # token too: input.x vs input.y, or different method names,
            # are different content even with identical children
            extra = [repr(getattr(node, attr)) for attr in
                     ("_key", "_kind", "_method") if hasattr(node, attr)]
            content[id(node)] = (
                type(node).__name__ + ":" +
                hashlib.sha1("|".join(child_tokens + extra).encode())
                .hexdigest()[:8])
            continue
        name = getattr(node._fn._function, "__name__", "step")

        def scrub(v):
            if isinstance(v, DAGNode):
                return "<node>"        # upstream identity rides
            if isinstance(v, (list, tuple)):    # child_tokens instead
                return [scrub(x) for x in v]
            if isinstance(v, dict):
                return {k: scrub(x) for k, x in sorted(
                    v.items(), key=lambda kv: repr(kv[0]))}
            return v

        statics = _static_repr((scrub(list(node._bound_args)),
                                scrub(node._bound_kwargs)))
        digest = hashlib.sha1("|".join(
            [_code_hash(node._fn._function), *child_tokens, statics]
        ).encode()).hexdigest()[:10]
        base = f"{name}_{digest}"
        n = seen_count.get(base, 0)
        seen_count[base] = n + 1
        sid = base if n == 0 else f"{base}_{n}"
        ids[id(node)] = sid
        content[id(node)] = sid
    return ids


class WorkflowCancelledError(RuntimeError):
    pass


_MAX_CONTINUATION_DEPTH = 200


def _execute_durable(dag: DAGNode, storage: _Storage, dag_input,
                     prefix: str = "", depth: int = 0) -> Any:
    """Ready-wave scheduler: completed steps replay from storage; all steps
    whose dependencies are resolved are submitted *together*, then results
    are consumed as they complete (ray_tpu.wait) and checkpointed — so
    independent branches run in parallel, like the non-durable execute().

    Continuations (reference: workflow_executor.py:32): a step whose
    result is itself a DAGNode recurses into this executor with the
    step's id as the namespace prefix; the sub-DAG's result becomes the
    step's checkpointed result, so resumes replay at every level.
    """
    from ray_tpu.dag import (ClassMethodNode, ClassNode,
                             InputAttributeNode, MultiOutputNode)
    if depth > _MAX_CONTINUATION_DEPTH:
        raise RecursionError(
            f"workflow continuation depth exceeded "
            f"{_MAX_CONTINUATION_DEPTH} (non-terminating recursion?)")
    nodes = _topo_order(dag)
    step_ids = {k: prefix + sid for k, sid in _step_ids(nodes).items()}
    resolved: Dict[int, Any] = {}
    inflight: Dict[str, tuple] = {}   # ref id -> (node key, step id, ref)

    def deps_ready(node: DAGNode) -> bool:
        return all(id(c) in resolved for c in node._children())

    def settle(key: int, sid: str, result: Any,
               replayed: bool = False) -> None:
        """Checkpoint a completed step, recursing into a returned DAG."""
        if isinstance(result, DAGNode):
            if not replayed:
                storage.save_continuation(sid, result)
            result = _execute_durable(
                result, storage, dag_input, prefix=sid + "__",
                depth=depth + 1)
        storage.save_step(sid, result)
        resolved[key] = result

    def check_cancelled() -> None:
        meta = storage.load_meta()
        if meta is not None and meta.get("status") == "CANCELED":
            raise WorkflowCancelledError(
                f"workflow was cancelled ({storage.dir})")

    while id(dag) not in resolved:
        progressed = False
        for node in nodes:
            key = id(node)
            if key in resolved or not deps_ready(node):
                continue
            # all children are in `resolved`, so _resolve_args/_execute_memo
            # hit the memo and never trigger non-durable execution
            if isinstance(node, FunctionNode):
                sid = step_ids[key]
                if storage.has_step(sid):
                    resolved[key] = storage.load_step(sid)
                    progressed = True
                elif storage.has_continuation(sid):
                    # the step ran before the crash and returned a DAG:
                    # continue the saved sub-DAG, don't re-run the step
                    settle(key, sid, storage.load_continuation(sid),
                           replayed=True)
                    progressed = True
                elif not any(k == key for k, _, _ in inflight.values()):
                    args, kwargs = node._resolve_args(resolved, dag_input)
                    ref = node._fn.remote(*args, **kwargs)
                    inflight[ref._id] = (key, sid, ref)
                continue
            if isinstance(node, ClassMethodNode):
                # durable mode keeps step inputs/outputs concrete, so the
                # method's ObjectRef is resolved here rather than passed on
                rs, kwargs = node._resolve_args(resolved, dag_input)
                handle, args = rs[0], rs[1:]
                resolved[key] = ray_tpu.get(
                    getattr(handle, node._method).remote(*args, **kwargs))
            elif isinstance(node, (InputNode, InputAttributeNode, ClassNode,
                                   MultiOutputNode)):
                resolved[key] = node._execute_impl(resolved, dag_input)
            else:
                raise TypeError(
                    f"unsupported DAG node {type(node).__name__}")
            progressed = True
        if id(dag) in resolved:
            break
        if inflight:
            check_cancelled()
            # consume ONE completed step, checkpoint it, then loop: newly
            # unblocked steps get submitted before we wait again
            refs = [ref for _, _, ref in inflight.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=None)
            key, sid, ref = inflight.pop(ready[0]._id)
            settle(key, sid, ray_tpu.get(ref))
        elif not progressed:
            raise RuntimeError("workflow DAG made no progress (cycle?)")
    return resolved[id(dag)]


# ---------------------------------------------------------------------------
# API (reference: workflow/api.py)
# ---------------------------------------------------------------------------

@dataclass
class WorkflowStatus:
    workflow_id: str
    # RUNNING | SUCCESSFUL | FAILED | RESUMABLE (RESUMABLE = the recorded
    # runner process is gone but the run never reached a terminal state,
    # e.g. kill -9 mid-run; resume() picks it up from its checkpoints)
    status: str
    created_ts: float


def _effective_status(meta: dict) -> str:
    status = meta["status"]
    if status == "RUNNING":
        pid = meta.get("pid")
        if pid is not None and pid != os.getpid():
            try:
                os.kill(pid, 0)
            except OSError:
                return "RESUMABLE"
    return status


def _input_hash(dag_input) -> str:
    import hashlib

    import cloudpickle
    try:
        return hashlib.sha1(cloudpickle.dumps(dag_input)).hexdigest()
    except Exception:
        return "unhashable"


def run(dag: DAGNode, *, workflow_id: str | None = None,
        dag_input=None) -> Any:
    """Execute a DAG durably; returns the final result. Re-running with the
    same workflow_id replays completed steps from storage; re-running with
    a *different* dag_input under the same id is rejected (old checkpoints
    would silently mix with the new input) — delete() first or use a new id.
    """
    workflow_id = workflow_id or f"wf_{int(time.time() * 1e6):x}"
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    ih = _input_hash(dag_input)
    if meta is not None and meta.get("input_hash") not in (None, ih):
        raise ValueError(
            f"workflow {workflow_id!r} was started with a different "
            "dag_input; its checkpoints would be inconsistent with the new "
            "input. workflow.delete() it or pick a new workflow_id.")
    if meta is not None and _effective_status(meta) == "RUNNING" \
            and meta.get("pid") != os.getpid():
        raise ValueError(
            f"workflow {workflow_id!r} is currently running in process "
            f"{meta.get('pid')}; concurrent duplicate execution would race "
            "on checkpoints.")
    if meta is None or meta["status"] != "SUCCESSFUL":
        storage.save_dag(dag, dag_input)
        storage.save_meta({"status": "RUNNING", "created_ts": time.time(),
                           "workflow_id": workflow_id, "input_hash": ih,
                           "pid": os.getpid()})
    try:
        result = _execute_durable(dag, storage, dag_input)
    except WorkflowCancelledError:
        raise                      # meta already says CANCELED
    except BaseException:
        m = storage.load_meta() or {}
        m["status"] = "FAILED"
        storage.save_meta(m)
        raise
    storage.save_step("__output__", result)
    m = storage.load_meta() or {}
    m["status"] = "SUCCESSFUL"
    storage.save_meta(m)
    return result


def resume(workflow_id: str) -> Any:
    """Resume a failed/interrupted workflow from its last checkpointed
    step (reference: api.resume)."""
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    if meta["status"] == "SUCCESSFUL":
        return storage.load_step("__output__")
    if _effective_status(meta) == "RUNNING" \
            and meta.get("pid") != os.getpid():
        raise ValueError(
            f"workflow {workflow_id!r} is currently running in process "
            f"{meta.get('pid')}; wait for it or workflow.delete() first.")
    dag, dag_input = storage.load_dag()
    meta["status"] = "RUNNING"
    meta["pid"] = os.getpid()
    storage.save_meta(meta)
    try:
        result = _execute_durable(dag, storage, dag_input)
    except WorkflowCancelledError:
        raise                      # meta already says CANCELED
    except BaseException:
        meta["status"] = "FAILED"
        storage.save_meta(meta)
        raise
    storage.save_step("__output__", result)
    meta["status"] = "SUCCESSFUL"
    storage.save_meta(meta)
    return result


def get_output(workflow_id: str) -> Any:
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    if meta is None or meta["status"] != "SUCCESSFUL":
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status: {meta and meta['status']})")
    return storage.load_step("__output__")


def get_status(workflow_id: str) -> str:
    meta = _Storage(workflow_id).load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return _effective_status(meta)


def list_all() -> list[WorkflowStatus]:
    root = _root()
    out = []
    for wid in sorted(os.listdir(root)):
        meta_path = os.path.join(root, wid, "meta.json")
        if not os.path.exists(meta_path):
            continue
        with open(meta_path) as f:
            m = json.load(f)
        out.append(WorkflowStatus(wid, _effective_status(m),
                                  m.get("created_ts", 0)))
    return out


def delete(workflow_id: str) -> None:
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


def cancel(workflow_id: str) -> None:
    """Request cancellation (reference: api.cancel). The running
    executor observes the CANCELED status at its next step boundary and
    raises WorkflowCancelledError; checkpoints are kept, so resume() can
    pick the run back up later."""
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] in ("SUCCESSFUL",):
        return
    meta["status"] = "CANCELED"
    storage.save_meta(meta)


def resume_all() -> Dict[str, Any]:
    """Resume every resumable/failed/cancelled workflow (reference:
    api.resume_all). Returns {workflow_id: result | exception}."""
    out: Dict[str, Any] = {}
    for st in list_all():
        if st.status in ("RESUMABLE", "FAILED", "CANCELED"):
            try:
                out[st.workflow_id] = resume(st.workflow_id)
            except Exception as e:      # surface, don't abort the batch
                out[st.workflow_id] = e
    return out


__all__ = ["init", "run", "resume", "get_output", "get_status",
           "list_all", "delete", "cancel", "resume_all",
           "WorkflowCancelledError", "WorkflowStatus"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("workflow")
del _rlu
