"""Durable workflows: DAGs whose step results persist across failures.

Counterpart of the reference's `python/ray/workflow/` (10k LoC):
`workflow_executor.py:32` drives a state machine over the DAG,
`workflow_storage.py:229` persists every step result so a crashed or
killed run resumes from the last completed step, `api.py` exposes
run/resume/list/get_output. Here the executor walks the `ray_tpu.dag`
expression tree; each FunctionNode becomes a durable *step* whose result
is checkpointed to storage (filesystem dir, one file per step) before the
next step may consume it. Step identity is positional (deterministic
topological index + function name), so resuming re-binds results to the
same steps as long as the DAG shape is unchanged — the same contract as
the reference's name-indexed steps.

Limitations vs reference (documented, not hidden): no virtual actors
(deprecated upstream), no cross-workflow events; ClassNode/actor steps
execute but are not checkpointed (actors are stateful; the reference
workflow layer likewise only checkpoints function steps).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

_storage_root: Optional[str] = None


def init(storage: str | None = None) -> None:
    """Set the durable storage root (default: RAY_TPU_WORKFLOW_DIR or
    ~/.ray_tpu/workflows)."""
    global _storage_root
    _storage_root = storage or os.environ.get(
        "RAY_TPU_WORKFLOW_DIR",
        os.path.expanduser("~/.ray_tpu/workflows"))
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    if _storage_root is None:
        init()
    return _storage_root


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_root(), workflow_id)


# ---------------------------------------------------------------------------
# storage (reference: workflow_storage.py)
# ---------------------------------------------------------------------------

class _Storage:
    def __init__(self, workflow_id: str):
        self.dir = _wf_dir(workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    def save_meta(self, meta: dict):
        tmp = os.path.join(self.dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    def load_meta(self) -> dict | None:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def save_dag(self, dag: DAGNode, dag_input):
        import cloudpickle
        tmp = os.path.join(self.dir, "dag.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump((dag, dag_input), f)
        os.replace(tmp, os.path.join(self.dir, "dag.pkl"))

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, step_id + ".pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))   # atomic commit

    def load_step(self, step_id: str):
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# executor (reference: workflow_executor.py run_until_complete :72)
# ---------------------------------------------------------------------------

def _topo_order(dag: DAGNode) -> list[DAGNode]:
    """Children-first deterministic topological order (shared nodes once)."""
    seen: Dict[int, bool] = {}
    order: list[DAGNode] = []

    def visit(node: DAGNode):
        if id(node) in seen:
            return
        seen[id(node)] = True
        for child in node._children():
            visit(child)
        order.append(node)
    visit(dag)
    return order


def _step_ids(nodes: list[DAGNode]) -> Dict[int, str]:
    """Deterministic step id per FunctionNode: topological visit order +
    function name. Stable across resumes for an unchanged DAG shape."""
    order: Dict[int, str] = {}
    counter = 0
    for node in nodes:
        if isinstance(node, FunctionNode):
            name = getattr(node._fn._function, "__name__", "step")
            order[id(node)] = f"{counter:05d}_{name}"
            counter += 1
    return order


def _execute_durable(dag: DAGNode, storage: _Storage, dag_input) -> Any:
    """Ready-wave scheduler: completed steps replay from storage; all steps
    whose dependencies are resolved are submitted *together*, then results
    are consumed as they complete (ray_tpu.wait) and checkpointed — so
    independent branches run in parallel, like the non-durable execute()."""
    from ray_tpu.dag import (ClassMethodNode, ClassNode,
                             InputAttributeNode, MultiOutputNode)
    nodes = _topo_order(dag)
    step_ids = _step_ids(nodes)
    resolved: Dict[int, Any] = {}
    inflight: Dict[str, tuple] = {}   # ref id -> (node key, step id, ref)

    def deps_ready(node: DAGNode) -> bool:
        return all(id(c) in resolved for c in node._children())

    while id(dag) not in resolved:
        progressed = False
        for node in nodes:
            key = id(node)
            if key in resolved or not deps_ready(node):
                continue
            # all children are in `resolved`, so _resolve_args/_execute_memo
            # hit the memo and never trigger non-durable execution
            if isinstance(node, FunctionNode):
                sid = step_ids[key]
                if storage.has_step(sid):
                    resolved[key] = storage.load_step(sid)
                    progressed = True
                elif not any(k == key for k, _, _ in inflight.values()):
                    args, kwargs = node._resolve_args(resolved, dag_input)
                    ref = node._fn.remote(*args, **kwargs)
                    inflight[ref._id] = (key, sid, ref)
                continue
            if isinstance(node, ClassMethodNode):
                # durable mode keeps step inputs/outputs concrete, so the
                # method's ObjectRef is resolved here rather than passed on
                rs, kwargs = node._resolve_args(resolved, dag_input)
                handle, args = rs[0], rs[1:]
                resolved[key] = ray_tpu.get(
                    getattr(handle, node._method).remote(*args, **kwargs))
            elif isinstance(node, (InputNode, InputAttributeNode, ClassNode,
                                   MultiOutputNode)):
                resolved[key] = node._execute_impl(resolved, dag_input)
            else:
                raise TypeError(
                    f"unsupported DAG node {type(node).__name__}")
            progressed = True
        if id(dag) in resolved:
            break
        if inflight:
            # consume ONE completed step, checkpoint it, then loop: newly
            # unblocked steps get submitted before we wait again
            refs = [ref for _, _, ref in inflight.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=None)
            key, sid, ref = inflight.pop(ready[0]._id)
            result = ray_tpu.get(ref)
            storage.save_step(sid, result)
            resolved[key] = result
        elif not progressed:
            raise RuntimeError("workflow DAG made no progress (cycle?)")
    return resolved[id(dag)]


# ---------------------------------------------------------------------------
# API (reference: workflow/api.py)
# ---------------------------------------------------------------------------

@dataclass
class WorkflowStatus:
    workflow_id: str
    # RUNNING | SUCCESSFUL | FAILED | RESUMABLE (RESUMABLE = the recorded
    # runner process is gone but the run never reached a terminal state,
    # e.g. kill -9 mid-run; resume() picks it up from its checkpoints)
    status: str
    created_ts: float


def _effective_status(meta: dict) -> str:
    status = meta["status"]
    if status == "RUNNING":
        pid = meta.get("pid")
        if pid is not None and pid != os.getpid():
            try:
                os.kill(pid, 0)
            except OSError:
                return "RESUMABLE"
    return status


def _input_hash(dag_input) -> str:
    import hashlib

    import cloudpickle
    try:
        return hashlib.sha1(cloudpickle.dumps(dag_input)).hexdigest()
    except Exception:
        return "unhashable"


def run(dag: DAGNode, *, workflow_id: str | None = None,
        dag_input=None) -> Any:
    """Execute a DAG durably; returns the final result. Re-running with the
    same workflow_id replays completed steps from storage; re-running with
    a *different* dag_input under the same id is rejected (old checkpoints
    would silently mix with the new input) — delete() first or use a new id.
    """
    workflow_id = workflow_id or f"wf_{int(time.time() * 1e6):x}"
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    ih = _input_hash(dag_input)
    if meta is not None and meta.get("input_hash") not in (None, ih):
        raise ValueError(
            f"workflow {workflow_id!r} was started with a different "
            "dag_input; its checkpoints would be inconsistent with the new "
            "input. workflow.delete() it or pick a new workflow_id.")
    if meta is not None and _effective_status(meta) == "RUNNING" \
            and meta.get("pid") != os.getpid():
        raise ValueError(
            f"workflow {workflow_id!r} is currently running in process "
            f"{meta.get('pid')}; concurrent duplicate execution would race "
            "on checkpoints.")
    if meta is None or meta["status"] != "SUCCESSFUL":
        storage.save_dag(dag, dag_input)
        storage.save_meta({"status": "RUNNING", "created_ts": time.time(),
                           "workflow_id": workflow_id, "input_hash": ih,
                           "pid": os.getpid()})
    try:
        result = _execute_durable(dag, storage, dag_input)
    except BaseException:
        m = storage.load_meta() or {}
        m["status"] = "FAILED"
        storage.save_meta(m)
        raise
    storage.save_step("__output__", result)
    m = storage.load_meta() or {}
    m["status"] = "SUCCESSFUL"
    storage.save_meta(m)
    return result


def resume(workflow_id: str) -> Any:
    """Resume a failed/interrupted workflow from its last checkpointed
    step (reference: api.resume)."""
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    if meta["status"] == "SUCCESSFUL":
        return storage.load_step("__output__")
    if _effective_status(meta) == "RUNNING" \
            and meta.get("pid") != os.getpid():
        raise ValueError(
            f"workflow {workflow_id!r} is currently running in process "
            f"{meta.get('pid')}; wait for it or workflow.delete() first.")
    dag, dag_input = storage.load_dag()
    meta["status"] = "RUNNING"
    meta["pid"] = os.getpid()
    storage.save_meta(meta)
    try:
        result = _execute_durable(dag, storage, dag_input)
    except BaseException:
        meta["status"] = "FAILED"
        storage.save_meta(meta)
        raise
    storage.save_step("__output__", result)
    meta["status"] = "SUCCESSFUL"
    storage.save_meta(meta)
    return result


def get_output(workflow_id: str) -> Any:
    storage = _Storage(workflow_id)
    meta = storage.load_meta()
    if meta is None or meta["status"] != "SUCCESSFUL":
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status: {meta and meta['status']})")
    return storage.load_step("__output__")


def get_status(workflow_id: str) -> str:
    meta = _Storage(workflow_id).load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return _effective_status(meta)


def list_all() -> list[WorkflowStatus]:
    root = _root()
    out = []
    for wid in sorted(os.listdir(root)):
        meta_path = os.path.join(root, wid, "meta.json")
        if not os.path.exists(meta_path):
            continue
        with open(meta_path) as f:
            m = json.load(f)
        out.append(WorkflowStatus(wid, _effective_status(m),
                                  m.get("created_ts", 0)))
    return out


def delete(workflow_id: str) -> None:
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


__all__ = ["init", "run", "resume", "get_output", "get_status",
           "list_all", "delete", "WorkflowStatus"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("workflow")
del _rlu
