"""Developer tooling that ships with the repo (static analysis, etc.)."""
