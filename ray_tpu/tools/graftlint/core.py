"""Rule engine: file discovery, waiver parsing, driving, reporting.

Exit-code contract: 0 = clean (all findings waived or none), 1 = active
findings, 2 = usage error (bad path, unknown rule, syntax error in a
linted file is reported as a finding, not an exit-2).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from functools import cached_property

from ray_tpu.tools.graftlint import astutil

# Repo root: three levels up from this file's directory.
REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))

WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<next>-next-line)?="
    r"(?P<rules>[A-Z][0-9]{3}(?:,[A-Z][0-9]{3})*)"
    r"(?P<reason>.*)$")

# Anything that looks like a waiver comment but fails WAIVER_RE.
_WAIVER_PROBE = re.compile(r"#\s*graftlint:\s*disable")

# Waiver-syntax findings (never themselves waivable).
W001 = "W001"


@dataclasses.dataclass
class Finding:
    rule: str
    file: str          # repo-relative posix path (or absolute if outside)
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        tag = f" [waived: {self.waiver_reason}]" if self.waived else ""
        return (f"{self.file}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}{tag}")


@dataclasses.dataclass
class Waiver:
    line: int          # line the comment sits on
    rules: tuple[str, ...]
    reason: str
    next_line: bool


def parse_waivers(lines: list[str], rel: str) \
        -> tuple[list[Waiver], list[Finding]]:
    waivers, findings = [], []
    for i, text in enumerate(lines, start=1):
        m = WAIVER_RE.search(text)
        if m is None:
            if _WAIVER_PROBE.search(text):
                findings.append(Finding(
                    W001, rel, i, 0,
                    "malformed graftlint waiver (expected "
                    "'graftlint: disable=R00X <reason>' in a comment)"))
            continue
        reason = m.group("reason").strip()
        rules = tuple(m.group("rules").split(","))
        if not reason:
            findings.append(Finding(
                W001, rel, i, m.start(),
                f"waiver for {','.join(rules)} is missing a reason — "
                "reasons are mandatory"))
            continue
        waivers.append(Waiver(i, rules, reason,
                              m.group("next") is not None))
    return waivers, findings


class FileContext:
    """Everything a rule needs about one file, computed lazily once."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        rel = os.path.relpath(os.path.abspath(path), REPO_ROOT)
        self.rel = rel.replace(os.sep, "/") if not rel.startswith("..") \
            else os.path.abspath(path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        astutil.add_parents(tree)
        self.waivers, self.waiver_findings = parse_waivers(
            self.lines, self.rel)

    @cached_property
    def qualnames(self):
        return astutil.qualnames(self.tree)

    @cached_property
    def jits(self):
        return astutil.build_jit_index(self.tree, self.qualnames)

    @cached_property
    def classes(self):
        return astutil.class_methods(self.tree)

    def apply_waivers(self, findings: list[Finding]) -> None:
        by_line: dict[int, list[Waiver]] = {}
        for w in self.waivers:
            by_line.setdefault(w.line + 1 if w.next_line else w.line,
                               []).append(w)
        for f in findings:
            if f.rule == W001:
                continue
            for w in by_line.get(f.line, []):
                if f.rule in w.rules:
                    f.waived = True
                    f.waiver_reason = w.reason
                    break


def _rule_modules():
    from ray_tpu.tools.graftlint.rules import ALL_RULES
    return ALL_RULES


def lint_file(path: str, select: set[str] | None = None,
              disable: set[str] | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(os.path.abspath(path), REPO_ROOT)
    rel = rel.replace(os.sep, "/") if not rel.startswith("..") else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding("E999", rel, exc.lineno or 1, exc.offset or 0,
                        f"syntax error: {exc.msg}")]
    ctx = FileContext(path, source, tree)
    findings: list[Finding] = list(ctx.waiver_findings)
    for rule_id, mod in _rule_modules().items():
        if select is not None and rule_id not in select:
            continue
        if disable is not None and rule_id in disable:
            continue
        findings.extend(mod.check(ctx))
    ctx.apply_waivers(findings)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py") and os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    return out


def lint_paths(paths: list[str], select: set[str] | None = None,
               disable: set[str] | None = None) \
        -> tuple[list[Finding], int]:
    files = iter_python_files(paths)
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_file(f, select=select, disable=disable))
    return findings, len(files)


def to_json(findings: list[Finding], files_scanned: int) -> dict:
    active = [f for f in findings if not f.waived]
    return {
        "version": 1,
        "files_scanned": files_scanned,
        "findings": [f.to_json() for f in findings],
        "counts": {
            "total": len(findings),
            "waived": len(findings) - len(active),
            "active": len(active),
        },
    }


def format_text(findings: list[Finding], files_scanned: int,
                show_waived: bool = False) -> str:
    lines = []
    for f in findings:
        if f.waived and not show_waived:
            continue
        lines.append(str(f))
    active = sum(1 for f in findings if not f.waived)
    waived = len(findings) - active
    lines.append(f"{active} finding(s) ({waived} waived) "
                 f"across {files_scanned} file(s)")
    return "\n".join(lines)
