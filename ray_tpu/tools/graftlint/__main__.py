"""CLI: python -m ray_tpu.tools.graftlint <paths> [--json] [...]"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.tools.graftlint import core


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant checker for ray_tpu "
                    "(see ray_tpu/tools/graftlint/RULES.md)")
    parser.add_argument("paths", nargs="+",
                        help="files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="emit machine-readable JSON")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--disable", default=None,
                        help="comma-separated rule ids to skip")
    parser.add_argument("--show-waived", action="store_true",
                        help="include waived findings in text output")
    try:
        args = parser.parse_args(argv)
    except SystemExit as exc:
        return 2 if exc.code not in (0, None) else 0

    select = set(args.select.split(",")) if args.select else None
    disable = set(args.disable.split(",")) if args.disable else None
    from ray_tpu.tools.graftlint.rules import ALL_RULES
    for rid in (select or set()) | (disable or set()):
        if rid not in ALL_RULES:
            print(f"graftlint: unknown rule {rid!r}", file=sys.stderr)
            return 2

    try:
        findings, nfiles = core.lint_paths(args.paths, select=select,
                                           disable=disable)
    except FileNotFoundError as exc:
        print(f"graftlint: no such path: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(core.to_json(findings, nfiles), indent=2))
    else:
        print(core.format_text(findings, nfiles,
                               show_waived=args.show_waived))
    return 1 if any(not f.waived for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
