"""R004 lock discipline.

Two checks:

1. **Blocking under lock.** Inside `with <lock>:` bodies — following
   same-class method calls up to 3 levels deep — flag calls that can
   block: `time.sleep`, `ray_tpu.get`/`ray_tpu.wait`, `.result()`,
   `.wait()`, `.join()`, queue `.get()`/`.put()`, device syncs
   (`jax.device_get`, `np.asarray`, `.block_until_ready()`,
   `jax.device_put`). A blocked holder of the engine scheduler lock
   stalls every stream's tick.

2. **Lock-order graph.** Nested acquisitions (lexical or via the same
   recursive walk) are edges; in registered files every observed edge
   must be declared in `scopes.LOCK_ORDER`, and the union of declared
   and observed edges must be acyclic.

Lock identity: for files registered in `scopes.LOCKS` the with-expr
dotted name is matched against the declared map (locks with
`blocking_ok=True` — e.g. the engine swap mutex, which exists precisely
to hold blocking placement away from the scheduler — skip check 1 but
still participate in check 2). For unregistered files, any with-expr
whose last segment ends in 'lock'/'mutex' (case-insensitive) is treated
as a lock named '<expr>'.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.tools.graftlint import astutil, scopes
from ray_tpu.tools.graftlint.core import Finding

RULE = "R004"

_GENERIC_LOCK = re.compile(r"(lock|mutex)s?$", re.IGNORECASE)
_MAX_DEPTH = 3

_BLOCKING_TAILS = {"result", "wait", "join", "block_until_ready",
                   "device_put"}
_EXACT_BLOCKING = {"time.sleep", "ray_tpu.get", "ray_tpu.wait"}


def _blocking_reason(name: str) -> str | None:
    parts = name.split(".")
    tail = parts[-1]
    if name in _EXACT_BLOCKING:
        return f"{name}() blocks"
    if tail in _BLOCKING_TAILS and len(parts) >= 2:
        return f".{tail}() can block indefinitely"
    if tail in ("device_get", "_device_get") or name == "_device_get":
        return f"{name}() is a device sync"
    if len(parts) == 2 and parts[0] in ("np", "numpy") and \
            tail == "asarray":
        return f"{name}() is a device sync"
    if tail in ("get", "put") and len(parts) >= 2 and \
            "queue" in parts[-2].lower():
        return f"{name}() can block on the queue"
    return None


def _lock_spec(ctx, expr: ast.AST) -> scopes.LockSpec | None:
    name = astutil.dotted_name(expr)
    if name is None:
        return None
    declared = scopes.LOCKS.get(ctx.rel)
    if declared is not None:
        return declared.get(name)
    if ctx.rel.startswith("ray_tpu/"):
        return None   # in-repo files must declare their locks
    if _GENERIC_LOCK.search(name.split(".")[-1]):
        return scopes.LockSpec(name)
    return None


def check(ctx) -> list[Finding]:
    findings: list[Finding] = []
    seen_lines: set[tuple[int, str]] = set()
    observed_edges: dict[tuple[str, str], int] = {}
    methods_by_class = ctx.classes

    def class_of(fn) -> dict | None:
        qual = ctx.qualnames.get(fn)
        if qual and "." in qual:
            return methods_by_class.get(qual.split(".")[0])
        return None

    def scan_node(node, held: list[scopes.LockSpec], cls, depth: int,
                  visited: frozenset):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return   # not executed at this point in the flow
        if isinstance(node, ast.With):
            specs = []
            for item in node.items:
                spec = _lock_spec(ctx, item.context_expr)
                if spec is not None:
                    specs.append(spec)
                else:
                    scan_node(item.context_expr, held, cls, depth,
                              visited)
            if specs:
                new_held = list(held)
                for spec in specs:
                    for h in new_held:
                        if h.name != spec.name:
                            observed_edges.setdefault(
                                (h.name, spec.name), node.lineno)
                    # reentrant re-acquire of the same (R)Lock is not
                    # a new edge and not a new hold level
                    if all(h.name != spec.name for h in new_held):
                        new_held.append(spec)
                for stmt in node.body:
                    scan_node(stmt, new_held, cls, depth, visited)
                return
        if isinstance(node, ast.Call):
            cname = astutil.call_name_loose(node)
            if cname is not None and held:
                innermost_strict = next(
                    (s for s in reversed(held) if not s.blocking_ok),
                    None)
                reason = _blocking_reason(cname)
                if reason is not None and innermost_strict is not None:
                    key = (node.lineno, innermost_strict.name)
                    if key not in seen_lines:
                        seen_lines.add(key)
                        findings.append(Finding(
                            RULE, ctx.rel, node.lineno, node.col_offset,
                            f"{reason} while holding lock "
                            f"'{innermost_strict.name}'"))
                # follow self.method() calls within the class
                parts = cname.split(".")
                if cls is not None and depth < _MAX_DEPTH and \
                        len(parts) == 2 and parts[0] == "self" and \
                        parts[1] in cls and parts[1] not in visited:
                    target = cls[parts[1]]
                    for stmt in target.body:
                        scan_node(stmt, held, cls, depth + 1,
                                  visited | {parts[1]})
        for child in ast.iter_child_nodes(node):
            scan_node(child, held, cls, depth, visited)

    # entry points: every `with <lock>:` not already inside another
    # lock-with (nested ones are reached by the scan itself)
    for fn, qual in ctx.qualnames.items():
        cls = class_of(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            if not any(_lock_spec(ctx, it.context_expr) is not None
                       for it in node.items):
                continue
            outer = getattr(node, "parent", None)
            enclosed = False
            while outer is not None and outer is not fn:
                if isinstance(outer, ast.With) and any(
                        _lock_spec(ctx, it.context_expr) is not None
                        for it in outer.items):
                    enclosed = True
                    break
                if isinstance(outer, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    break   # nested def: its own entry point
                outer = getattr(outer, "parent", None)
            if not enclosed:
                scan_node(node, [], cls, 0, frozenset())

    # module-level with-locks (e.g. telemetry's registry lock) live in
    # functions too — covered above since qualnames maps all defs; a
    # with-lock at true module scope is rare and skipped.

    # lock-order: observed edges must be declared (registered files),
    # and declared ∪ observed must be acyclic
    declared = set(scopes.LOCK_ORDER)
    in_registry = ctx.rel in scopes.LOCKS
    for edge, lineno in sorted(observed_edges.items(),
                               key=lambda kv: kv[1]):
        if in_registry and edge not in declared:
            findings.append(Finding(
                RULE, ctx.rel, lineno, 0,
                f"undeclared lock-order edge {edge[0]} -> {edge[1]} — "
                "declare it in scopes.LOCK_ORDER or restructure"))
    graph: dict[str, set[str]] = {}
    for a, b in declared | set(observed_edges):
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycle = _find_cycle(graph)
    if cycle is not None:
        involved = [observed_edges[e] for e in observed_edges
                    if e[0] in cycle and e[1] in cycle]
        if involved:   # only report where an edge is visible
            findings.append(Finding(
                RULE, ctx.rel, min(involved), 0,
                "lock-order cycle: " + " -> ".join(cycle)))
    return findings


def _find_cycle(graph: dict[str, set[str]]) -> list[str] | None:
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph[n]):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                found = dfs(m)
                if found is not None:
                    return found
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            found = dfs(n)
            if found is not None:
                return found
    return None
