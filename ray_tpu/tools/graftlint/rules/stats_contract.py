"""R005 stats-contract.

For every function named `stats` whose body returns a single dict
literal with all-constant string keys (no `**` spread), the keys
documented in its docstring as ``key`` must match the returned keys
exactly, both directions. This is the static twin of
test_telemetry.py's runtime docstring-contract check: the Prometheus
bridge auto-registers one series per stats() key, so an undocumented
key is an unreviewed series and a documented-but-missing key is a dead
dashboard panel.

Functions whose stats() builds the dict dynamically (returns a
variable, uses `**`, computed keys) or whose docstring documents no
``key`` tokens are skipped — the contract only binds where both sides
are statically known.
"""

from __future__ import annotations

import ast
import re

from ray_tpu.tools.graftlint import astutil
from ray_tpu.tools.graftlint.core import Finding

RULE = "R005"

_DOC_KEY = re.compile(r"``([A-Za-z0-9_]+)``")


def _returned_dict(fn) -> ast.Dict | None:
    """The dict literal if every return in fn returns the same literal
    shape we can check; else None."""
    returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
    dicts = [r.value for r in returns if isinstance(r.value, ast.Dict)]
    if len(dicts) != 1 or len(returns) != 1:
        return None
    return dicts[0]


def check(ctx) -> list[Finding]:
    findings = []
    for fn, qual in ctx.qualnames.items():
        if fn.name != "stats":
            continue
        doc = ast.get_docstring(fn)
        if not doc:
            continue
        documented = set(_DOC_KEY.findall(doc))
        if not documented:
            continue
        d = _returned_dict(fn)
        if d is None:
            continue
        keys = set()
        static = True
        for k in d.keys:
            if k is None:                       # ** spread
                static = False
                break
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
            else:
                static = False
                break
        if not static:
            continue
        undocumented = sorted(keys - documented)
        missing = sorted(documented - keys)
        if undocumented:
            findings.append(Finding(
                RULE, ctx.rel, fn.lineno, fn.col_offset,
                f"{qual}() returns keys not documented in its "
                f"docstring: {', '.join(undocumented)}"))
        if missing:
            findings.append(Finding(
                RULE, ctx.rel, fn.lineno, fn.col_offset,
                f"{qual}() docstring documents keys it does not "
                f"return: {', '.join(missing)}"))
    return findings
