"""R003 retrace hazards + compile-once inventory.

Checks, everywhere:

- Python `if`/`while` on a traced *parameter* of a jitted function
  (trace-time branching on device values raises ConcretizationError or
  silently bakes one branch in).
- A jitted function reading a module-level mutable literal (list/dict/
  set): mutating it between calls changes trace-time constants and
  forces silent retraces.
- `jax.jit(...)` created inside a `for`/`while` loop: a fresh jit per
  iteration defeats the compile cache.
- Unhashable (list/dict/set literal) or f-string arguments at positions
  declared static via static_argnums/static_argnames: every distinct
  object retraces.

Plus, for files registered in `scopes.COMPILE_ONCE_JITS`: every jit
anchor in the file must appear in the inventory — the same registry
RetraceSentinel validates `registered=True` watches against — so adding
a new jitted hot path without registering it fails at lint time.
"""

from __future__ import annotations

import ast

from ray_tpu.tools.graftlint import astutil, scopes
from ray_tpu.tools.graftlint.core import Finding

RULE = "R003"

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _param_names(args: ast.arguments) -> set[str]:
    names = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _module_mutable_globals(tree: ast.AST) -> set[str]:
    out = set()
    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, _MUTABLE_LITERALS):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _inventory_findings(ctx) -> list[Finding]:
    inventory = scopes.COMPILE_ONCE_JITS.get(ctx.rel)
    if inventory is None:
        return []
    findings = []
    seen = set()
    for info in ctx.jits.all:
        if info.anchor in seen:
            continue
        seen.add(info.anchor)
        if info.anchor not in inventory:
            findings.append(Finding(
                RULE, ctx.rel, info.lineno, 0,
                f"jit anchor '{info.anchor}' is not in the compile-once "
                "inventory (ray_tpu/tools/graftlint/scopes.py "
                "COMPILE_ONCE_JITS) — register it and arm a "
                "RetraceSentinel watch, or mark it None with a reason"))
    return findings


def check(ctx) -> list[Finding]:
    findings = _inventory_findings(ctx)
    mutable_globals = _module_mutable_globals(ctx.tree)

    # per-jitted-body hazards
    for info, args, body in ctx.jits.jitted_bodies():
        params = _param_names(args)
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.While)):
                    for ref in ast.walk(node.test):
                        if isinstance(ref, ast.Name) and \
                                isinstance(ref.ctx, ast.Load) and \
                                ref.id in params:
                            findings.append(Finding(
                                RULE, ctx.rel, node.lineno,
                                node.col_offset,
                                f"in jitted fn '{info.anchor}': Python "
                                f"branch on traced param '{ref.id}' — "
                                "use lax.cond/jnp.where"))
                            break
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in mutable_globals and \
                        node.id not in params:
                    findings.append(Finding(
                        RULE, ctx.rel, node.lineno, node.col_offset,
                        f"in jitted fn '{info.anchor}': reads mutable "
                        f"module global '{node.id}' — mutations force "
                        "silent retraces"))

    # jax.jit inside a loop
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.For, ast.While)):
            for sub in ast.walk(node):
                if astutil.is_jit_call(sub):
                    findings.append(Finding(
                        RULE, ctx.rel, sub.lineno, sub.col_offset,
                        "jax.jit() constructed inside a loop — hoist it "
                        "out or the compile cache is defeated"))

    # unhashable / f-string args at declared-static positions
    static_jits = {a.split(".")[-1]: i for a, i in ctx.jits.by_anchor.items()
                   if i.static_argnums or i.static_argnames}
    if static_jits:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = astutil.call_name(node)
            if cname is None:
                continue
            info = static_jits.get(cname.split(".")[-1])
            if info is None:
                continue
            bad_args: list[ast.AST] = []
            for pos in info.static_argnums:
                if pos < len(node.args):
                    bad_args.append(node.args[pos])
            for kw in node.keywords:
                if kw.arg in info.static_argnames:
                    bad_args.append(kw.value)
            for arg in bad_args:
                if isinstance(arg, _MUTABLE_LITERALS):
                    findings.append(Finding(
                        RULE, ctx.rel, arg.lineno, arg.col_offset,
                        f"unhashable literal passed at a static arg of "
                        f"{cname}() — every call retraces"))
                elif isinstance(arg, ast.JoinedStr):
                    findings.append(Finding(
                        RULE, ctx.rel, arg.lineno, arg.col_offset,
                        f"f-string passed at a static arg of {cname}() "
                        "— every distinct string retraces"))

    uniq: dict[tuple, Finding] = {}
    for f in findings:
        uniq.setdefault((f.line, f.col, f.message), f)
    return list(uniq.values())
