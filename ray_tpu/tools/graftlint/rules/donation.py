"""R002 use-after-donate.

For every call site of a donating callable (a jax.jit with
donate_argnums in this file, or the result of a registered donating
factory like `fuse_steps`), any Name / self-attribute passed at a
donated position must not be read again before it is reassigned: after
dispatch the buffer is dead, and reading it returns garbage (or a
deleted-buffer error on real hardware).

A donated arg whose name is also a target of the same statement
(`state, m = step(state, batch)`) is the canonical clean pattern. For
anything else we do a linear scan over the statements that follow the
call in source order (including the loop body before the call when the
call sits inside a loop — the next iteration re-executes it): the first
Load of the name before a full reassignment is a finding.
"""

from __future__ import annotations

import ast

from ray_tpu.tools.graftlint import astutil, scopes
from ray_tpu.tools.graftlint.core import Finding

RULE = "R002"


def _donating_callables(ctx) -> dict[str, tuple[int, ...]]:
    """Last-segment callable name -> donated argnums."""
    out: dict[str, tuple[int, ...]] = {}
    for anchor, info in ctx.jits.by_anchor.items():
        if info.donate:
            out[anchor.split(".")[-1]] = info.donate
        elif info.donate_unknown:
            # `jax.jit(f, **kwargs)` — if the factory is registered we
            # know its donation contract; otherwise assume argnum 0,
            # the overwhelmingly common convention, to stay on the
            # conservative side.
            fac = anchor.split(".")[-1]
            out[fac] = scopes.DONATING_FACTORIES.get(fac, (0,))
    # Anchors assigned from a registered donating factory (possibly
    # through an IfExp): `self._dispatch = step if ... else
    # fuse_steps(...)` — calls through the anchor donate.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        values = [node.value]
        if isinstance(node.value, ast.IfExp):
            values = [node.value.body, node.value.orelse]
        for value in values:
            if not isinstance(value, ast.Call):
                continue
            vname = astutil.call_name(value)
            if vname is None:
                continue
            donate = scopes.DONATING_FACTORIES.get(vname.split(".")[-1])
            if donate is None:
                continue
            for t in node.targets:
                an = astutil.dotted_name(t)
                if an is not None:
                    out[an.split(".")[-1]] = donate
    return out


def _loads_name(stmt: ast.stmt, name: str) -> ast.AST | None:
    """First Load of dotted `name` in stmt, ignoring Store contexts."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Name, ast.Attribute)) and \
                isinstance(getattr(node, "ctx", None), ast.Load) and \
                astutil.dotted_name(node) == name:
            return node
    return None


def _enclosing_stmt_chain(node: ast.AST) -> list[ast.stmt]:
    """All statements on the parent chain of `node` (innermost first)."""
    out = []
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, ast.stmt):
            out.append(cur)
        cur = getattr(cur, "parent", None)
    return out


def _function_stmts(fn) -> list[ast.stmt]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and node is not fn:
            out.append(node)
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out


def check(ctx) -> list[Finding]:
    donators = _donating_callables(ctx)
    if not donators:
        return []
    findings = []
    for fn, qual in ctx.qualnames.items():
        stmts = None
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            cname = astutil.call_name(call)
            if cname is None:
                continue
            tail = cname.split(".")[-1]
            donate = donators.get(tail)
            if donate is None:
                continue
            if tail in scopes.DONATING_FACTORIES:
                # calling the factory itself (fuse_steps(...)) does not
                # donate — only calls through its *result* do, and
                # those go through an assigned anchor name.
                continue
            chain = _enclosing_stmt_chain(call)
            if not chain:
                continue
            call_stmt = chain[0]
            same_stmt_targets = set(astutil.stmt_assigned_names(call_stmt))
            for pos in donate:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                name = astutil.dotted_name(arg)
                if name is None or name == "self":
                    continue
                if name in same_stmt_targets:
                    continue   # `x, y = f(x)` — reassigned on return
                if stmts is None:
                    stmts = _function_stmts(fn)
                # statements after the call, plus (for calls inside a
                # loop) the loop body from its top — next iteration
                # re-reads anything left unassigned.
                loop = next((s for s in chain
                             if isinstance(s, (ast.For, ast.While))), None)
                seq = [s for s in stmts
                       if s.lineno > call_stmt.lineno]
                if loop is not None:
                    seq += [s for s in stmts
                            if loop.lineno < s.lineno <= call_stmt.lineno
                            and s is not call_stmt]
                bad = None
                for stmt in seq:
                    load = _loads_name(stmt, name)
                    if load is not None:
                        bad = load
                        break
                    if name in astutil.stmt_assigned_names(stmt):
                        break   # fully reassigned; buffer is live again
                if bad is not None:
                    findings.append(Finding(
                        RULE, ctx.rel, bad.lineno, bad.col_offset,
                        f"'{name}' donated to {cname}() at line "
                        f"{call.lineno} is read again before "
                        "reassignment"))
    return findings
