"""R001 host-sync-in-hot-path.

Two sweeps:

1. Inside every resolvable jax.jit-wrapped function body (these run
   under trace — a host sync there is either a trace-time crash or a
   silent constant-folding bug): `.item()`, `np.asarray`/`np.array`,
   `jax.device_get`, `.block_until_ready()`, `print`, and `float()`/
   `int()` on non-constants.

2. Inside registered hot scopes (`scopes.HOT_SCOPES` — the engine tick,
   TrainLoop.run's step body, etc., which are host code but
   latency-critical): device syncs (`np.asarray`, `jax.device_get`,
   `.item()`, `.block_until_ready()`), `print`, `time.sleep`, queue
   receives/puts/joins, and `int()`/`float()` applied to values freshly
   returned by a jitted callable (the classic accidental sync on a
   device array).
"""

from __future__ import annotations

import ast

from ray_tpu.tools.graftlint import astutil, scopes
from ray_tpu.tools.graftlint.core import Finding

RULE = "R001"

_NP = ("np", "numpy")


def _is_np_asarray(name: str) -> bool:
    parts = name.split(".")
    return len(parts) == 2 and parts[0] in _NP and parts[1] == "asarray"


def _is_device_get(name: str) -> bool:
    return name.split(".")[-1] in ("device_get", "_device_get") \
        or name == "_device_get"


def _is_queueish(name: str) -> bool:
    """Receiver of .get/.put/.join that is plausibly a queue (so plain
    dict.get / set ops don't light up)."""
    parts = name.split(".")
    return len(parts) >= 2 and parts[-1] in ("get", "put", "join") \
        and "queue" in parts[-2].lower()


def _jit_body_findings(ctx) -> list[Finding]:
    findings = []
    for info, args, body in ctx.jits.jitted_bodies():
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.call_name_loose(node)
                if name is None:
                    continue
                tail = name.split(".")[-1]
                msg = None
                if name == "print":
                    msg = "print() under jit traces every call"
                elif tail == "item" and "." in name:
                    msg = ".item() is a host sync"
                elif _is_np_asarray(name) or (
                        name.split(".")[0] in _NP and tail == "array"):
                    msg = f"{name}() pulls the traced value to host"
                elif _is_device_get(name):
                    msg = f"{name}() is a host sync"
                elif tail == "block_until_ready":
                    msg = ".block_until_ready() is a host sync"
                elif name in ("float", "int") and node.args and not \
                        isinstance(node.args[0], ast.Constant):
                    msg = (f"{name}() on a traced value forces "
                           "concretization")
                if msg is not None:
                    findings.append(Finding(
                        RULE, ctx.rel, node.lineno, node.col_offset,
                        f"in jitted fn '{info.anchor}': {msg}"))
    return findings


def _jitted_callable_attrs(ctx) -> set[str]:
    """Last path segment of every jit anchor in this file, e.g.
    '_prefill_fn' from 'self._prefill_fn'."""
    return {a.split(".")[-1] for a in ctx.jits.by_anchor}


def _hot_scope_findings(ctx) -> list[Finding]:
    hot = scopes.HOT_SCOPES.get(ctx.rel)
    if not hot:
        return []
    findings = []
    jit_attrs = _jitted_callable_attrs(ctx)
    for fn, qual in ctx.qualnames.items():
        if qual not in hot:
            continue
        # Names bound (possibly via tuple unpack) from jitted-callable
        # calls inside this scope — int()/float() on them is a sync.
        device_names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                cname = astutil.call_name(node.value)
                if cname is not None and \
                        cname.split(".")[-1] in jit_attrs:
                    for t in node.targets:
                        for n in astutil.assigned_names(t):
                            if "." not in n:
                                device_names.add(n)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name_loose(node)
            # bare np.asarray / device_get passed as an argument
            # (e.g. jax.tree.map(np.asarray, tree)) syncs too
            for arg in node.args:
                aname = astutil.dotted_name(arg)
                if aname and (_is_np_asarray(aname)
                              or _is_device_get(aname)):
                    findings.append(Finding(
                        RULE, ctx.rel, arg.lineno, arg.col_offset,
                        f"in hot scope '{qual}': {aname} mapped over a "
                        "tree is a host sync"))
            if name is None:
                continue
            tail = name.split(".")[-1]
            msg = None
            if name == "print":
                msg = "print() blocks the tick on stdout"
            elif tail == "item" and "." in name:
                msg = ".item() is a device sync"
            elif _is_np_asarray(name):
                msg = f"{name}() is a device sync"
            elif _is_device_get(name):
                msg = f"{name}() is a device sync"
            elif tail == "block_until_ready":
                msg = ".block_until_ready() stalls the pipeline"
            elif name == "time.sleep":
                msg = "time.sleep() stalls the hot path"
            elif _is_queueish(name):
                msg = f"{name}() can block the hot path"
            elif name in ("float", "int") and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in device_names:
                msg = (f"{name}({node.args[0].id}) syncs on a value "
                       "just returned by a jitted callable")
            if msg is not None:
                findings.append(Finding(
                    RULE, ctx.rel, node.lineno, node.col_offset,
                    f"in hot scope '{qual}': {msg}"))
    return findings


def check(ctx) -> list[Finding]:
    return _jit_body_findings(ctx) + _hot_scope_findings(ctx)
