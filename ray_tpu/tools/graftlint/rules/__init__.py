"""Rule registry. Each module exposes `check(ctx) -> list[Finding]`."""

from ray_tpu.tools.graftlint.rules import (
    donation,
    hot_sync,
    locks,
    retrace,
    stats_contract,
)

ALL_RULES = {
    "R001": hot_sync,
    "R002": donation,
    "R003": retrace,
    "R004": locks,
    "R005": stats_contract,
}
