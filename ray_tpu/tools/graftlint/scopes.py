"""Scope registries: the repo's declared hot paths, compile-once jits,
locks, and lock-order graph.

This module is the single source of truth shared by the static rules and
by the runtime: `RetraceSentinel.watch(..., registered=True)` validates
its watch name against RETRACE_WATCHES, so adding a new jitted hot path
without registering it here fails loudly at engine construction — and
adding a jit assignment to a registered file without an inventory entry
fails R003 at lint time. Paths are repo-relative posix.

Deliberately dependency-free: importable from ray_tpu.util.telemetry
without dragging the linter (or jax) in.
"""

from __future__ import annotations

ENGINE = "ray_tpu/serve/engine.py"
LOOP = "ray_tpu/train/loop.py"
FT = "ray_tpu/train/ft.py"
FLYWHEEL = "ray_tpu/rl/flywheel.py"
SPMD = "ray_tpu/train/spmd.py"
PREDICTOR = "ray_tpu/train/predictor.py"
CONTROLLER = "ray_tpu/serve/controller.py"
REPLICA = "ray_tpu/serve/replica.py"
HANDLE = "ray_tpu/serve/handle.py"
DISAGG = "ray_tpu/serve/disagg.py"
TELEMETRY = "ray_tpu/util/telemetry.py"
METRICS = "ray_tpu/util/metrics.py"
FAULTS = "ray_tpu/util/faults.py"
TRACING = "ray_tpu/util/tracing.py"
EVENTS = "ray_tpu/_private/events.py"
WORKER_MAIN = "ray_tpu/_private/worker_main.py"
NETADDR = "ray_tpu/_private/netaddr.py"

# --- R001: functions whose bodies are latency-critical host code. A
# host sync here stalls the device queue (or the scheduler tick).
HOT_SCOPES: dict[str, frozenset[str]] = {
    ENGINE: frozenset({
        "InferenceEngine.step",
        "InferenceEngine.tokens_for",
        "InferenceEngine._try_admit",
        "InferenceEngine._admit_pending",
        "InferenceEngine._batch_arrays",
        "InferenceEngine._run_prefill_chunk",
        "InferenceEngine._prefill_tick",
        "InferenceEngine._decode_tick",
        "InferenceEngine._spec_tick",
        "InferenceEngine._emit",
        # priority/preemption plane — all run inside the scheduler tick
        # under engine.scheduler (the admission queue shares self._lock;
        # no new lock, so no new LOCK_ORDER edges)
        "InferenceEngine._admission_order",
        "InferenceEngine._pick_victim",
        "InferenceEngine._preempt",
        "InferenceEngine._force_preempt",
        "InferenceEngine._admit_or_preempt",
        "InferenceEngine._shed_lowest_below",
        # disaggregated prefill/decode handoff plane — export runs in
        # the prefill-completion tick, import admission inside step();
        # both under engine.scheduler (no new lock, no new LOCK_ORDER
        # edges)
        "InferenceEngine._export_handoff",
        "InferenceEngine._admit_imports",
        "InferenceEngine._try_import",
        "InferenceEngine.handoff_for",
    }),
    LOOP: frozenset({
        "TrainLoop.run",
        "MetricsRing.push",
        "MetricsRing._sync",
        "DevicePrefetcher.__next__",
    }),
    FT: frozenset({
        "AsyncCheckpointer.maybe_snapshot",
        "AsyncCheckpointer.flush",
    }),
    FLYWHEEL: frozenset({
        "FlywheelLoop._publish",
    }),
    # span-drain path: runs on every TaskDone seal / metrics flush, and
    # _record sits inside span() on every traced hot-path operation
    TRACING: frozenset({
        "_record",
        "drain_spans",
        "ingest",
    }),
    EVENTS: frozenset({
        "TaskEventRecorder._collect_stages_locked",
    }),
    WORKER_MAIN: frozenset({
        "WorkerRuntime._drain_spans_for_push",
    }),
}

# --- R003: compile-once inventory. For each registered file, every
# `<anchor> = jax.jit(...)` assignment (or factory returning a jit) must
# appear here; the value is the RetraceSentinel watch name guarding it,
# or None for jits that are deliberately unwatched (cheap, cold, or
# traced a bounded number of times by construction).
COMPILE_ONCE_JITS: dict[str, dict[str, str | None]] = {
    ENGINE: {
        "self._prefill_fn": "prefill",
        "self._decode_fn": "decode",
        "self._copy_fn": None,          # COW block copy; shapes fixed
        "self._verify_fn": "verify",
        "self._propose_fn": "draft",
        "self._draft_prefill_fn": "draft_prefill",
        "self._swap_fn": "swap",
        "self._quantize_fn": "quantize",  # int8 weight-only path
        # disaggregated prefill/decode block transport (one trace per
        # pool geometry: target + optional draft pool)
        "self._gather_fn": "kv_gather",
        "self._scatter_block_fn": "kv_scatter",
    },
    LOOP: {
        "fuse_steps": "dispatch",       # factory: returns the fused jit
    },
    FT: {
        "self._copy": None,             # device-side snapshot clone
    },
    FLYWHEEL: {
        "self._step": None,             # watched via TrainLoop dispatch
    },
    SPMD: {
        "make_train_step": None,        # factory; callers own the watch
    },
    PREDICTOR: {
        "self._apply": None,            # one bucket set, traced per shape
    },
}

# The sentinel watch names that must be armed with registered=True.
RETRACE_WATCHES: frozenset[str] = frozenset(
    name
    for per_file in COMPILE_ONCE_JITS.values()
    for name in per_file.values()
    if name is not None
)

# --- R002: factories whose *returned* callable donates these argnums.
# Keyed by bare factory name; matched at call sites of the assigned
# target (e.g. `self._dispatch = fuse_steps(...)`).
DONATING_FACTORIES: dict[str, tuple[int, ...]] = {
    "fuse_steps": (0,),
    "make_train_step": (0,),
}


class LockSpec:
    """A declared lock. `blocking_ok` marks locks that exist to
    serialize an inherently blocking operation (e.g. the engine swap
    mutex, whose whole job is to hold device placement away from the
    scheduler lock); R004 skips the blocking-call check under them but
    still tracks them in the lock-order graph."""

    __slots__ = ("name", "blocking_ok")

    def __init__(self, name: str, blocking_ok: bool = False):
        self.name = name
        self.blocking_ok = blocking_ok


# --- R004: declared locks, keyed by file -> {with-expr dotted name}.
LOCKS: dict[str, dict[str, LockSpec]] = {
    ENGINE: {
        "self._lock": LockSpec("engine.scheduler"),
        "self._swap_mutex": LockSpec("engine.swap", blocking_ok=True),
    },
    CONTROLLER: {
        "self._lock": LockSpec("serve.controller"),
    },
    REPLICA: {
        "self._lock": LockSpec("serve.replica"),
    },
    HANDLE: {
        # router lock brackets routing state only — the failover/retry
        # work (controller RPCs, backoff sleeps) must never run under it
        "self._lock": LockSpec("serve.handle.router"),
        "self._router.lock": LockSpec("serve.handle.router"),
        "self._router.refresh_lock": LockSpec(
            "serve.handle.refresh", blocking_ok=True),
        "self._mu": LockSpec("serve.handle.stats"),
    },
    DISAGG: {
        # parked-handoff map / pull-stats state on both replica roles
        "self._lock": LockSpec("serve.disagg.state"),
        # serializes pull exchanges on the shared netaddr connection;
        # its whole job is to hold blocking wire recvs away from state
        "self._pull_mu": LockSpec("serve.disagg.pull", blocking_ok=True),
    },
    TELEMETRY: {
        "_lock": LockSpec("telemetry.registry"),
    },
    METRICS: {
        "self.lock": LockSpec("metrics.registry"),
        "self._lock": LockSpec("metrics.series"),
    },
    FAULTS: {
        "_lock": LockSpec("faults.registry"),
    },
    TRACING: {
        "_lock": LockSpec("tracing.ring"),
    },
    EVENTS: {
        # stage histograms are observed OUTSIDE this lock (durations are
        # collected under it, fed to metrics after release) — keep it
        # leaf-level: no metrics/tracing edges
        "self._lock": LockSpec("events.recorder"),
    },
    NETADDR: {
        # outbound-queue condition: senders wait under it for
        # backpressure credit, the flusher waits under it for work
        "self._qcv": LockSpec("netaddr.batch.queue", blocking_ok=True),
        # serializes wire writes; its whole job is to hold a (blocking)
        # socket send away from the queue state
        "self._wire_lock": LockSpec("netaddr.batch.wire",
                                    blocking_ok=True),
        # one-shot UDP interface probe memo
        "_advertise_lock": LockSpec("netaddr.advertise",
                                    blocking_ok=True),
    },
    WORKER_MAIN: {
        # pipelined-submission window: submitters wait under it when
        # the credit window is exhausted
        "self._sub_cv": LockSpec("worker.submit_window",
                                 blocking_ok=True),
    },
}

# Declared lock-order edges (may-acquire-while-holding). Observed
# nestings in registered files must be a subset; cycles in the union of
# declared and observed edges are findings.
LOCK_ORDER: frozenset[tuple[str, str]] = frozenset({
    ("engine.swap", "engine.scheduler"),
    ("engine.scheduler", "telemetry.registry"),
    ("telemetry.registry", "metrics.registry"),
    ("metrics.registry", "metrics.series"),
    # handle refresh: controller RPC under the blocking-ok refresh lock,
    # snapshot/commit under the router lock
    ("serve.handle.refresh", "serve.handle.router"),
    # frame flusher / send_bytes: pop the outbound queue while holding
    # the wire (send()'s opposite-direction wire probe is a
    # non-blocking try-acquire, so it adds no queue->wire edge)
    ("netaddr.batch.wire", "netaddr.batch.queue"),
})
