"""Shared AST analyses: parents, qualnames, dotted names, and the
per-file jax.jit registry."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


def add_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def call_name_loose(call: ast.Call) -> str | None:
    """Like call_name, but when the receiver chain is unresolvable
    (subscripts, chained calls) still yields '?.<attr>' so method-tail
    checks like `.item()` / `.result()` see through `x.mean().item()`
    and `futs[0].result()`."""
    name = dotted_name(call.func)
    if name is None and isinstance(call.func, ast.Attribute):
        return "?." + call.func.attr
    return name


def is_jit_call(node: ast.AST) -> bool:
    """Matches jax.jit / self._jax.jit / jit(...) call expressions."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name is None:
        return False
    parts = name.split(".")
    return parts[-1] == "jit" and (len(parts) == 1 or "jax" in parts[-2]
                                   or "jax" in parts[0])


FuncDef = ast.FunctionDef | ast.AsyncFunctionDef


def qualnames(tree: ast.AST) -> dict[FuncDef, str]:
    """Map each function def to its dotted qualname (Class.method)."""
    out: dict[FuncDef, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                out[child] = qn
                visit(child, qn + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def class_methods(tree: ast.AST) -> dict[str, dict[str, FuncDef]]:
    """class name -> {method name -> def} (top-level classes only)."""
    out: dict[str, dict[str, FuncDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            meths = {}
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    meths[item.name] = item
            out[node.name] = meths
    return out


@dataclass
class JitInfo:
    """One jax.jit site: where it was created and what it wraps."""

    anchor: str                      # assign target / factory qualname
    call: ast.Call                   # the jax.jit(...) call
    func_node: ast.AST | None = None # resolved wrapped fn (def or Lambda)
    donate: tuple[int, ...] = ()
    donate_unknown: bool = False     # **kwargs or non-literal donation
    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    kind: str = "assign"             # assign | return | decorator
    lineno: int = 0
    enclosing: FuncDef | None = None


def _int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    return None


def _str_tuple(node: ast.AST) -> tuple[str, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    return None


def _jit_kwargs(info: JitInfo) -> None:
    for kw in info.call.keywords:
        if kw.arg is None:
            info.donate_unknown = True        # jax.jit(f, **kwargs)
        elif kw.arg == "donate_argnums":
            t = _int_tuple(kw.value)
            if t is None:
                info.donate_unknown = True
            else:
                info.donate = t
        elif kw.arg == "static_argnums":
            info.static_argnums = _int_tuple(kw.value) or ()
        elif kw.arg == "static_argnames":
            info.static_argnames = _str_tuple(kw.value) or ()


def _resolve_func(call: ast.Call, tree: ast.AST) -> ast.AST | None:
    """Resolve jax.jit's first positional arg to a def/Lambda in-file."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Name):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == arg.id:
                return node
    return None   # attribute refs (self._method) are unresolvable


def _jit_in_value(value: ast.AST) -> ast.Call | None:
    """The jax.jit call inside an assign value, looking through one
    level of IfExp (e.g. `jax.jit(f) if cond else None`). Returns None
    for immediately-invoked jits like `jax.jit(f)(x)`."""
    cands = [value]
    if isinstance(value, ast.IfExp):
        cands = [value.body, value.orelse]
    for cand in cands:
        if is_jit_call(cand):
            return cand
    return None


@dataclass
class JitIndex:
    by_anchor: dict[str, JitInfo] = field(default_factory=dict)
    all: list[JitInfo] = field(default_factory=list)

    def jitted_bodies(self):
        """(info, params, body_stmts) for every resolvable wrapped fn."""
        for info in self.all:
            fn = info.func_node
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield info, fn.args, fn.body
            elif isinstance(fn, ast.Lambda):
                yield info, fn.args, [ast.Expr(value=fn.body)]


def build_jit_index(tree: ast.AST,
                    qn: dict[FuncDef, str] | None = None) -> JitIndex:
    """Find every jax.jit site: assignments (incl. through IfExp),
    `return jax.jit(...)` factories (anchored at the enclosing function
    name), and @jax.jit / @partial(jax.jit, ...) decorators."""
    qn = qn if qn is not None else qualnames(tree)
    index = JitIndex()

    def enclosing_func(node: ast.AST) -> FuncDef | None:
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "parent", None)
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            call = _jit_in_value(node.value)
            if call is None:
                continue
            for target in node.targets:
                anchor = dotted_name(target)
                if anchor is None:
                    continue
                info = JitInfo(anchor=anchor, call=call,
                               lineno=node.lineno,
                               enclosing=enclosing_func(node))
                info.func_node = _resolve_func(call, tree)
                _jit_kwargs(info)
                index.by_anchor[anchor] = info
                index.all.append(info)
        elif isinstance(node, ast.Return) and node.value is not None:
            call = _jit_in_value(node.value)
            if call is None:
                continue
            fn = enclosing_func(node)
            anchor = fn.name if fn is not None else "<module>"
            info = JitInfo(anchor=anchor, call=call, kind="return",
                           lineno=node.lineno, enclosing=fn)
            info.func_node = _resolve_func(call, tree)
            _jit_kwargs(info)
            index.by_anchor.setdefault(anchor, info)
            index.all.append(info)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                is_bare = dotted_name(deco) is not None and \
                    dotted_name(deco).split(".")[-1] == "jit"
                is_partial = (isinstance(deco, ast.Call)
                              and call_name(deco) is not None
                              and call_name(deco).split(".")[-1] == "partial"
                              and deco.args and is_jit_call_name(deco.args[0]))
                if is_bare or is_partial:
                    info = JitInfo(anchor=qn.get(node, node.name),
                                   call=deco if isinstance(deco, ast.Call)
                                   else ast.Call(func=deco, args=[],
                                                 keywords=[]),
                                   func_node=node, kind="decorator",
                                   lineno=node.lineno)
                    if isinstance(deco, ast.Call):
                        _jit_kwargs(info)
                    index.by_anchor.setdefault(info.anchor, info)
                    index.all.append(info)
                    break
    return index


def is_jit_call_name(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.split(".")[-1] == "jit"


def assigned_names(target: ast.AST) -> list[str]:
    """Dotted names stored by an assignment target (flattens tuples)."""
    out: list[str] = []
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out.extend(assigned_names(elt))
    else:
        name = dotted_name(target)
        if name is not None:
            out.append(name)
    return out


def stmt_assigned_names(stmt: ast.stmt) -> list[str]:
    if isinstance(stmt, ast.Assign):
        names = []
        for t in stmt.targets:
            names.extend(assigned_names(t))
        return names
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return assigned_names(stmt.target)
    return []
