"""graftlint: AST-based invariant checker for ray_tpu.

Rules (see RULES.md for the full reference):

- R001 host-sync-in-hot-path
- R002 use-after-donate
- R003 retrace hazards / compile-once inventory
- R004 lock discipline (blocking under lock + lock-order graph)
- R005 stats() docstring/dict contract

Run with ``python -m ray_tpu.tools.graftlint <paths>``.
"""

from ray_tpu.tools.graftlint.core import (  # noqa: F401
    Finding,
    lint_file,
    lint_paths,
)
