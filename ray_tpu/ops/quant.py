"""Int8 quantization primitives shared by the paged KV cache and the
weight-only decode matmuls.

One math, everywhere: the decode path is HBM-bandwidth-bound, so every
byte a K/V block or a weight read sheds converts directly into capacity
(more concurrent streams per HBM byte) and throughput. Both consumers
use symmetric absmax int8 with f32 scales and f32 accumulation:

- **KV rows** (`quantize_rows`): one scale per (position, head) row of
  the last axis — ``x [..., D] -> (q int8 [..., D], scale f32 [...])``.
  Per-row scales mean a single-token decode append writes its own scale
  cell with the same scatter index as its payload: no read-modify-write,
  no cross-token coupling, so COW block copies and speculative rollback
  need no special handling.
- **Weights** (`quantize_channels`): one scale per output channel —
  ``w [..., In, Out] -> (q int8 [..., In, Out], scale f32 [..., Out])``
  over the contraction axis, the standard weight-only recipe: the
  dequantized operand folds into the matmul's rhs read and accumulation
  stays f32.

Determinism contract: quantize-then-dequantize is a pure function of the
f32 input, so any path that writes the same K/V values (prefill scatter,
decode append, verify append) lands byte-identical int8 payloads and
scales — which is what keeps spec-decode verify bit-identical to
sequential decode, and shared-prefix/COW reads identical regardless of
which request populated the block.

Zero rows quantize to zero with scale 0 (the ``safe`` guard divides by 1
instead): dequantization maps them back to exact zeros, so the pool's
zero-init and the trash block stay inert.
"""

from __future__ import annotations

import jax.numpy as jnp

INT8_MAX = 127.0


def quantize_rows(x):
    """Symmetric per-row int8 over the LAST axis.

    ``x [..., D]`` (any float dtype) -> ``(q int8 [..., D],
    scale f32 [...])`` with ``scale = max(|x|, axis=-1) / 127`` and
    ``q = round(x / scale)`` clipped to [-127, 127]. All-zero rows get
    scale 0 and quantize to zeros (dequantizes to exact zeros)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = (amax / INT8_MAX).astype(jnp.float32)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe[..., None]),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scale):
    """Inverse of `quantize_rows`: ``(q int8 [..., D], scale f32 [...])``
    -> f32 ``[..., D]``."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]


def quantize_channels(w):
    """Symmetric per-output-channel int8 over axis -2 (the contraction
    axis of a ``[..., In, Out]`` weight).

    -> ``(q int8 [..., In, Out], scale f32 [..., Out])`` with
    ``scale = max(|w|, axis=-2) / 127``. All-zero channels get scale 0
    and dequantize to exact zeros."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2)
    scale = (amax / INT8_MAX).astype(jnp.float32)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(wf / safe[..., None, :]),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize_channels(q, scale):
    """Inverse of `quantize_channels`: ``(q int8 [..., In, Out],
    scale f32 [..., Out])`` -> f32 ``[..., In, Out]``."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None, :]
