"""Flash attention as a Pallas TPU kernel.

The reference has no custom kernels of its own (its GPU fast paths live in
torch/NCCL); on TPU the memory-bound op worth hand-scheduling is attention:
O(T^2) scores never touch HBM — K/V blocks stream through VMEM while
per-row running softmax statistics live in VMEM scratch across the
sequential kv grid dimension.

Layout: [B, T, H, D] public API (matching `ray_tpu.parallel.ring_attention`
so models switch impls freely). Internally [B*H, T, D], grid
(BH, T/block_q, T/block_kv) with the kv dimension innermost/sequential and
batch/query dimensions parallel.

Backward pass: `jax.custom_vjp` recomputes attention with the O(T^2) XLA
path (flash backward kernel is a later milestone); forward-dominated
workloads (inference, serving) get the full win now.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.parallel.ring_attention import reference_attention

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool,
                  block_q: int, block_kv: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_kv
    # Under causal masking, kv blocks strictly above the diagonal band
    # contribute nothing; predicate the whole body away.
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0].astype(jnp.float32)           # [bkv, D]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bkv]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kpos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [bq, bkv]
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=1,
                                                     keepdims=True)
        m_scr[:, :1] = m_new
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, D]
        acc_scr[:] = acc_scr[:] * corr + pv

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _flash_bhtd(q, k, v, *, sm_scale: float, causal: bool, block_q: int,
                block_kv: int, interpret: bool):
    """q,k,v: [BH, T, D] with T divisible by both block sizes."""
    bh, t, d = q.shape
    grid = (bh, t // block_q, t // block_kv)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_kv=block_kv)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _supported(t: int, block_q: int, block_kv: int) -> bool:
    return t % block_q == 0 and t % block_kv == 0 and t >= block_q


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_kv: int = 128):
    """[B, T, H, D] attention; falls back to the XLA path off-TPU-unfriendly
    shapes. Differentiable (backward = recomputed XLA attention)."""
    return _flash_forward_impl(q, k, v, causal, block_q, block_kv)


def _flash_forward_impl(q, k, v, causal, block_q, block_kv):
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_kv = min(block_kv, t)
    if not _supported(t, block_q, block_kv):
        return reference_attention(q, k, v, causal=causal)
    interpret = jax.default_backend() != "tpu"
    # Pad head_dim up to a multiple of the 128-lane tile; zero columns
    # change nothing (scores: zero contributions; output: sliced off).
    d_pad = -(-d // 128) * 128
    if d_pad != d:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad - d)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    bhtd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d_pad)
    out = _flash_bhtd(bhtd(q), bhtd(k), bhtd(v), sm_scale=d ** -0.5,
                      causal=causal, block_q=block_q, block_kv=block_kv,
                      interpret=interpret)
    out = out.reshape(b, h, t, d_pad).transpose(0, 2, 1, 3)
    return out[..., :d]


def _flash_fwd(q, k, v, causal, block_q, block_kv):
    return _flash_forward_impl(q, k, v, causal, block_q, block_kv), (q, k, v)


def _flash_bwd(causal, block_q, block_kv, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention(q, k, v, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
