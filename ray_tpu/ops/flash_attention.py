"""Flash attention as Pallas TPU kernels — forward AND backward.

The reference has no custom kernels of its own (its GPU fast paths live in
torch/NCCL); on TPU the memory-bound op worth hand-scheduling is attention:
O(T^2) scores never touch HBM — K/V blocks stream through VMEM while
per-row running softmax statistics live in VMEM scratch across the
sequential kv grid dimension.

Layout: [B, T, H, D] public API (matching `ray_tpu.parallel.ring_attention`
so models switch impls freely). Internally [B*H, T, D], grid
(BH, T/block_q, T/block_kv) with the kv dimension innermost/sequential and
batch/query dimensions parallel.

Backward pass: two more Pallas kernels (FlashAttention-2 style).  The
forward saves the per-row logsumexp; backward precomputes
``delta = rowsum(dO * O)`` in XLA (bandwidth-trivial), then

- the **dQ kernel** iterates kv blocks innermost, accumulating
  ``dq += ds @ k`` in VMEM scratch, and
- the **dKV kernel** iterates q blocks innermost, accumulating
  ``dv += p^T @ dO`` and ``dk += ds^T @ q``,

so the O(T^2) probability matrix is rebuilt block-by-block in VMEM and
never written to HBM in either direction.  Under causal masking, blocks
strictly above the diagonal are predicated away in all three kernels.

The forward-only (inference) path compiles a kernel variant with no lse
output, so serving never pays the lse write; the lse variant runs only
under autodiff.  lse/delta live as [BH, T, 128] f32 — broadcast across the
128-lane tile — because Mosaic requires output block last dims of 128 (a
[BH, T] row vector with (1, block_q) blocks fails its tiling check); the
stock JAX TPU flash kernel stores its lse the same way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x ships the TPU params dataclass as TPUCompilerParams; newer
# releases renamed it CompilerParams. Resolve once so the kernels run
# on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

from ray_tpu.parallel.ring_attention import reference_attention

NEG_INF = -1e30


def _causal_mask(s, q_start, k_start, block_q, block_kv):
    qpos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    kpos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest, sm_scale: float,
                  causal: bool, block_q: int, block_kv: int,
                  with_lse: bool):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_kv
    # Under causal masking, kv blocks strictly above the diagonal band
    # contribute nothing; predicate the whole body away.
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0].astype(jnp.float32)           # [bkv, D]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bkv]
        if causal:
            s = _causal_mask(s, q_start, k_start, block_q, block_kv)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [bq, bkv]
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(p, axis=1,
                                                     keepdims=True)
        m_scr[:, :1] = m_new
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, D]
        acc_scr[:] = acc_scr[:] * corr + pv

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)
        if with_lse:
            # lse broadcast across the 128-lane tile (TPU min tile width).
            lse_ref[0] = jnp.broadcast_to(
                m_scr[:, :1] + jnp.log(l_scr[:, :1]), lse_ref.shape[1:])


def _flash_bhtd(q, k, v, *, sm_scale: float, causal: bool, block_q: int,
                block_kv: int, interpret: bool, with_lse: bool):
    """q,k,v: [BH, T, D] with T divisible by both block sizes.

    Returns (out [BH, T, D], lse) where lse is [BH, T, 128] f32 (per-row
    logsumexp broadcast across the lane tile) when with_lse, else None."""
    bh, t, d = q.shape
    grid = (bh, t // block_q, t // block_kv)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_kv=block_kv, with_lse=with_lse)
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))]
    if with_lse:
        out_shape.append(jax.ShapeDtypeStruct((bh, t, 128), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)))
    res = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else (res[0], None)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    q_start, k_start, *, sm_scale: float, causal: bool,
                    block_q: int, block_kv: int):
    """Rebuild the probability block and dS from saved lse/delta — the
    shared core of both backward kernels, so a masking/scaling change can
    never diverge between dQ and dK/dV."""
    q = q_ref[0].astype(jnp.float32)            # [bq, D]
    k = k_ref[0].astype(jnp.float32)            # [bkv, D]
    v = v_ref[0].astype(jnp.float32)            # [bkv, D]
    do = do_ref[0].astype(jnp.float32)          # [bq, D]
    lse = lse_ref[0][:, :1]                     # [bq, 1]
    delta = delta_ref[0][:, :1]                 # [bq, 1]
    s = jax.lax.dot_general(
        q * sm_scale, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)     # [bq, bkv]
    if causal:
        s = _causal_mask(s, q_start, k_start, block_q, block_kv)
    p = jnp.exp(s - lse)                        # [bq, bkv]
    dp = jax.lax.dot_general(
        do, v,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)     # [bq, bkv]
    ds = p * (dp - delta)                       # [bq, bkv]
    return q, k, do, p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, sm_scale: float, causal: bool,
               block_q: int, block_kv: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_kv
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _body():
        _, k, _, _, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_kv=block_kv)
        dq_scr[:] += sm_scale * jax.lax.dot_general(
            ds, k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bq, D]

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale: float,
                causal: bool, block_q: int, block_kv: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_kv
    live = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(live)
    def _body():
        q, _, do, p, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_kv=block_kv)
        dv_scr[:] += jax.lax.dot_general(
            p, do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bkv, D]
        dk_scr[:] += sm_scale * jax.lax.dot_general(
            ds, q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bkv, D]

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_bhtd(q, k, v, do, lse, delta, *, sm_scale: float,
                    causal: bool, block_q: int, block_kv: int,
                    interpret: bool):
    """All inputs [BH, T, D] (lse/delta [BH, T, 128] f32) -> (dq, dk, dv)."""
    bh, t, d = q.shape
    common = dict(sm_scale=sm_scale, causal=causal,
                  block_q=block_q, block_kv=block_kv)

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    rowq = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, t // block_q, t // block_kv),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dKV grid: kv blocks parallel, q blocks innermost/sequential.
    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_kv, d), lambda b, j, i: (b, j, 0))
    rowq2 = pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)),
        grid=(bh, t // block_kv, t // block_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=(kspec2, kspec2),
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32),
                        pltpu.VMEM((block_kv, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _pick_block(t: int, pref: int) -> int | None:
    """Largest lane-aligned block <= pref that divides t, so raising the
    preferred block size never silently drops a shape the kernel handled
    at a smaller block (e.g. T=1536 runs at 768, not the XLA fallback)."""
    if t <= 128:
        return t
    b = min(pref, t) // 128 * 128
    while b >= 128:
        if t % b == 0:
            return b
        b -= 128
    return None


def _plan_blocks(t: int, block_q: int, block_kv: int):
    bq, bkv = _pick_block(t, block_q), _pick_block(t, block_kv)
    if bq is None or bkv is None:
        return None
    return bq, bkv


def _pad_heads(x, d_pad):
    d = x.shape[-1]
    if d_pad == d:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, d_pad - d)])


def _head_pad_target(d: int) -> int:
    """Mosaic accepts a last block dim equal to the full array dim, so any
    multiple of the 8-sublane tile works unpadded (64 for GPT heads); only
    ragged head dims pad up to the next 8-sublane multiple."""
    return d if d % 8 == 0 else -(-d // 8) * 8


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 1024,
                    block_kv: int = 1024):
    """[B, T, H, D] attention; falls back to the XLA path on
    TPU-unfriendly shapes. Fully differentiable: both directions are
    Pallas kernels (backward = dQ + dKV kernels over saved lse).

    Default blocks measured on v5e at the bench shape (B=8, T=1024, H=16,
    D=64): 1024/1024 > 512/1024 > 512/512 ≈ 128/128 on full train-step
    throughput (55.1k vs 51.3k vs 28.2k tok/s for the pre-backward-kernel
    XLA-recompute path). Blocks shrink to the largest divisor of T, so
    ragged sequence lengths stay on the kernel path."""
    out, _ = _flash_forward_impl(q, k, v, causal, block_q, block_kv,
                                 with_lse=False)
    return out


def _flash_forward_impl(q, k, v, causal, block_q, block_kv, with_lse):
    """Returns (out, lse|None). lse is None on the XLA fallback path or
    when with_lse=False (the inference variant, which skips the lse
    write entirely)."""
    b, t, h, d = q.shape
    plan = _plan_blocks(t, block_q, block_kv)
    if plan is None:
        return reference_attention(q, k, v, causal=causal), None
    block_q, block_kv = plan
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    bhtd = lambda x: (_pad_heads(x, d_pad)
                      .transpose(0, 2, 1, 3).reshape(b * h, t, d_pad))
    out, lse = _flash_bhtd(bhtd(q), bhtd(k), bhtd(v), sm_scale=d ** -0.5,
                           causal=causal, block_q=block_q,
                           block_kv=block_kv, interpret=interpret,
                           with_lse=with_lse)
    out = out.reshape(b, h, t, d_pad).transpose(0, 2, 1, 3)
    return out[..., :d], lse


def _flash_fwd(q, k, v, causal, block_q, block_kv):
    out, lse = _flash_forward_impl(q, k, v, causal, block_q, block_kv,
                                   with_lse=True)
    if lse is None:
        return out, (q, k, v, None, None)
    # The residual keeps the kernel's broadcast [BH, T, 128] lse layout.
    # Slicing to [BH, T] and re-broadcasting in bwd costs ~3% step time
    # (two extra 64 MB passes per layer at bench shape, measured 55.1k ->
    # 53.5k tok/s); under the default per-layer remat the residual only
    # lives within one layer's backward, so the 128x is transient. A
    # no-remat long-T config that can't afford it should slice here.
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_kv, res, g):
    q, k, v, out, lse = res
    if lse is None:   # XLA fallback path (static shape decision)
        _, vjp = jax.vjp(
            lambda q, k, v: reference_attention(q, k, v, causal=causal),
            q, k, v)
        return vjp(g)

    b, t, h, d = q.shape
    block_q, block_kv = _plan_blocks(t, block_q, block_kv)
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    # delta_i = rowsum(dO_i * O_i) — O(T*D) traffic, fine in XLA.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                          # [B, T, H]
    delta = delta.transpose(0, 2, 1).reshape(b * h, t)
    delta = jnp.broadcast_to(delta[..., None], (b * h, t, 128))
    bhtd = lambda x: (_pad_heads(x, d_pad)
                      .transpose(0, 2, 1, 3).reshape(b * h, t, d_pad))
    dq, dk, dv = _flash_bwd_bhtd(
        bhtd(q), bhtd(k), bhtd(v), bhtd(g), lse, delta,
        sm_scale=d ** -0.5, causal=causal, block_q=block_q,
        block_kv=block_kv, interpret=interpret)
    unbhtd = lambda x: (x.reshape(b, h, t, d_pad)
                        .transpose(0, 2, 1, 3)[..., :d])
    return unbhtd(dq), unbhtd(dk), unbhtd(dv)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
