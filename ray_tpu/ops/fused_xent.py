"""Fused chunked cross-entropy over a tied embedding — the LM loss
without the logits tensor.

The dense LM loss materializes logits ``[B, T, V]`` (the single biggest
activation in a GPT step: 1.6 GB f32 at the bench shape) just to reduce
it straight back down to one scalar per token. This op consumes the
pre-unembed activations ``x [B, T, d_model]`` and the tied embedding
``embed [V, d_model]`` instead, streaming the unembed matmul in vocab
chunks with an online (running max / log-sum-exp) accumulator — the
FlashAttention trick applied to the softmax over the vocabulary. Peak
live activation for the loss becomes O(B*T*chunk) instead of
O(B*T*V).

The backward is a `custom_vjp` that recomputes each chunk's logits from
the saved per-token logsumexp, so the residuals are just (x, embed,
targets, lse) — again no ``[B, T, V]`` anywhere:

    dlogits_c = g * (softmax_c - onehot_c)
    dx       += dlogits_c @ embed_c          (accumulated over chunks)
    dembed_c  = dlogits_c^T @ x              (one chunk per scan step)

Two implementations share that math:

- **pallas**: TPU forward + backward kernels (grid = rows x vocab
  blocks, per-row m/l/target-logit accumulators in VMEM scratch),
  mirroring flash_attention.py's structure.
- **scan**: a pure-JAX `lax.scan` over vocab chunks — the
  everywhere-correct fallback that CPU CI and `bench.py --smoke` run.

Vocab-sharded (tensor-parallel) embeddings compose through a
`shard_map` wrapper: each shard reduces its *local* vocab rows to a
partial logsumexp and partial target logit, then one psum over the
vocab mesh axis combines them (`parallel/sharding.fused_xent_specs`
derives the specs from the rule table). The collective moves two
``[B, T]`` f32 arrays — vs. the dense path's vocab-sharded logits
gather/reduction over ``[B, T, V]``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams (jax 0.4.x) vs CompilerParams (newer) — same
# resolve-once shim as flash_attention.py
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG = -1e30   # finite -inf stand-in: exp(_NEG - m) underflows to 0


# ---------------------------------------------------------------------------
# scan implementation (the everywhere-correct fallback)
# ---------------------------------------------------------------------------

def _chunked_embed(embed, chunk):
    """[V, D] -> ([nc, chunk, D], padded_v). Zero-padded rows are masked
    by callers via their column index (col < V)."""
    v, d = embed.shape
    nc = -(-v // chunk)
    vpad = nc * chunk
    if vpad != v:
        embed = jnp.pad(embed, ((0, vpad - v), (0, 0)))
    return embed.reshape(nc, chunk, d), vpad


def _lse_tgt_scan(x, embed, targets, chunk):
    """Partial stats over `embed`'s rows: per-token logsumexp [B, T] and
    raw target logit [B, T] (0 when the target id is outside [0, V) —
    the tensor-parallel shard case)."""
    v = embed.shape[0]
    chunk = min(chunk, v)
    emb, _ = _chunked_embed(embed, chunk)
    bt = x.shape[:-1]
    init = (jnp.full(bt, _NEG, jnp.float32),       # running max m
            jnp.zeros(bt, jnp.float32),            # sumexp at m
            jnp.zeros(bt, jnp.float32))            # target logit

    def body(carry, inp):
        m, l, tg = carry
        idx, e_c = inp
        s = jnp.einsum("btd,cd->btc", x, e_c,
                       preferred_element_type=jnp.float32)
        col = idx * chunk + jnp.arange(chunk, dtype=jnp.int32)
        valid = col < v
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        l = (l * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(s - m_new[..., None]), axis=-1))
        hit = (col == targets[..., None]) & valid
        tg = tg + jnp.sum(jnp.where(hit, s, 0.0), axis=-1)
        return (m_new, l, tg), None

    nc = emb.shape[0]
    (m, l, tg), _ = jax.lax.scan(body, init,
                                 (jnp.arange(nc, dtype=jnp.int32), emb))
    return m + jnp.log(l), tg


def _bwd_scan(x, embed, targets, lse, c_lse, c_tgt, chunk):
    """Recompute per-chunk logits from the saved lse and emit f32
    (dx [B, T, D], dembed [V, D]). c_lse/c_tgt are the cotangents of the
    partial (lse, target-logit) pair — (g, -g) for the plain nll."""
    v, d = embed.shape
    chunk = min(chunk, v)
    emb, vpad = _chunked_embed(embed, chunk)

    def body(dx, inp):
        idx, e_c = inp
        s = jnp.einsum("btd,cd->btc", x, e_c,
                       preferred_element_type=jnp.float32)
        col = idx * chunk + jnp.arange(chunk, dtype=jnp.int32)
        valid = col < v
        p = jnp.where(valid, jnp.exp(s - lse[..., None]), 0.0)
        hit = ((col == targets[..., None]) & valid).astype(jnp.float32)
        dlog = c_lse[..., None] * p + c_tgt[..., None] * hit
        dx = dx + jnp.einsum("btc,cd->btd", dlog, e_c,
                             preferred_element_type=jnp.float32)
        de_c = jnp.einsum("btc,btd->cd", dlog, x,
                          preferred_element_type=jnp.float32)
        return dx, de_c

    nc = emb.shape[0]
    dx, de = jax.lax.scan(body, jnp.zeros(x.shape, jnp.float32),
                          (jnp.arange(nc, dtype=jnp.int32), emb))
    return dx, de.reshape(vpad, d)[:v]


# ---------------------------------------------------------------------------
# pallas kernels (TPU)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, e_ref, t_ref, lse_ref, tgt_ref,
                m_scr, l_scr, t_scr, *, block_v):
    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    x = x_ref[...].astype(jnp.float32)              # [bn, D]
    e = e_ref[...].astype(jnp.float32)              # [bv, D]
    s = jax.lax.dot_general(
        x, e, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)         # [bn, bv]
    col = ji * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_scr[:, :1] = (l_scr[:, :1] * jnp.exp(m_prev - m_new)
                    + jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True))
    m_scr[:, :1] = m_new
    hit = col == t_ref[:, :1]
    t_scr[:, :1] += jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True)

    @pl.when(ji == pl.num_programs(1) - 1)
    def _finalize():
        lse = m_scr[:, :1] + jnp.log(l_scr[:, :1])
        # broadcast across the 128-lane tile (TPU min tile width)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        tgt_ref[...] = jnp.broadcast_to(t_scr[:, :1], tgt_ref.shape)


def _recompute_dlog(x_ref, e_ref, t_ref, lse_ref, cl_ref, ct_ref,
                    v_start):
    """Rebuild one logits block from the saved lse and form dlogits —
    shared by the dx and dembed kernels so the masking/softmax math can
    never diverge between them (flash_attention._recompute_p_ds idiom)."""
    x = x_ref[...].astype(jnp.float32)              # [bn, D]
    e = e_ref[...].astype(jnp.float32)              # [bv, D]
    s = jax.lax.dot_general(
        x, e, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)         # [bn, bv]
    col = v_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.exp(s - lse_ref[:, :1])
    hit = (col == t_ref[:, :1]).astype(jnp.float32)
    dlog = cl_ref[:, :1] * p + ct_ref[:, :1] * hit  # [bn, bv]
    return x, e, dlog


def _dx_kernel(x_ref, e_ref, t_ref, lse_ref, cl_ref, ct_ref, dx_ref,
               dx_scr, *, block_v):
    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        dx_scr[:] = jnp.zeros_like(dx_scr)

    _, e, dlog = _recompute_dlog(x_ref, e_ref, t_ref, lse_ref, cl_ref,
                                 ct_ref, ji * block_v)
    dx_scr[:] += jax.lax.dot_general(
        dlog, e, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [bn, D]

    @pl.when(ji == pl.num_programs(1) - 1)
    def _finalize():
        dx_ref[...] = dx_scr[:]


def _de_kernel(x_ref, e_ref, t_ref, lse_ref, cl_ref, ct_ref, de_ref,
               de_scr, *, block_v):
    # grid is (vocab blocks, row blocks): rows are the inner sequential
    # dim so the dembed accumulator lives in scratch across them
    ii = pl.program_id(1)

    @pl.when(ii == 0)
    def _init():
        de_scr[:] = jnp.zeros_like(de_scr)

    x, _, dlog = _recompute_dlog(x_ref, e_ref, t_ref, lse_ref, cl_ref,
                                 ct_ref, pl.program_id(0) * block_v)
    de_scr[:] += jax.lax.dot_general(
        dlog, x, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [bv, D]

    @pl.when(ii == pl.num_programs(1) - 1)
    def _finalize():
        de_ref[...] = de_scr[:]


def _rows128(a, n):
    """[B, T] -> [N, 128] f32/int32 broadcast across the lane tile."""
    return jnp.broadcast_to(a.reshape(n, 1), (n, 128))


def _lse_tgt_pallas(x, embed, targets, block_n, block_v, interpret):
    b, t, d = x.shape
    n = b * t
    v = embed.shape[0]
    grid = (n // block_n, v // block_v)
    row_spec = pl.BlockSpec((block_n, 128), lambda i, j: (i, 0))
    lse2, tgt2 = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v),
        out_shape=(jax.ShapeDtypeStruct((n, 128), jnp.float32),) * 2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            row_spec,
        ],
        out_specs=(row_spec, row_spec),
        scratch_shapes=[pltpu.VMEM((block_n, 128), jnp.float32)] * 3,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x.reshape(n, d), embed, _rows128(targets.astype(jnp.int32), n))
    return lse2[:, 0].reshape(b, t), tgt2[:, 0].reshape(b, t)


def _bwd_pallas(x, embed, targets, lse, c_lse, c_tgt, block_n, block_v,
                interpret):
    b, t, d = x.shape
    n = b * t
    v = embed.shape[0]
    x2 = x.reshape(n, d)
    t2 = _rows128(targets.astype(jnp.int32), n)
    lse2 = _rows128(lse.astype(jnp.float32), n)
    cl2 = _rows128(c_lse.astype(jnp.float32), n)
    ct2 = _rows128(c_tgt.astype(jnp.float32), n)
    row_spec = pl.BlockSpec((block_n, 128), lambda i, j: (i, 0))

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_v=block_v),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        grid=(n // block_n, v // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            row_spec, row_spec, row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2, embed, t2, lse2, cl2, ct2)

    # swapped grid: each vocab block streams every row block through its
    # accumulator
    row_spec_t = pl.BlockSpec((block_n, 128), lambda j, i: (i, 0))
    de = pl.pallas_call(
        functools.partial(_de_kernel, block_v=block_v),
        out_shape=jax.ShapeDtypeStruct((v, d), jnp.float32),
        grid=(v // block_v, n // block_n),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            row_spec_t, row_spec_t, row_spec_t, row_spec_t,
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2, embed, t2, lse2, cl2, ct2)
    return dx.reshape(b, t, d), de


# ---------------------------------------------------------------------------
# implementation dispatch
# ---------------------------------------------------------------------------

def _pick(t: int, pref: int, step: int) -> int | None:
    """Largest step-aligned block <= pref that divides t (the
    flash_attention._pick_block divisor search; step=128 for the lane
    dim, 8 for the sublane dim)."""
    b = min(pref, t) // step * step
    while b >= step:
        if t % b == 0:
            return b
        b -= step
    return None


def _plan(n: int, v: int, block_n: int, block_v: int):
    bn, bv = _pick(n, block_n, 8), _pick(v, block_v, 128)
    return (bn, bv) if bn and bv else None


def _resolve_impl(impl: str, n: int, v: int, chunk: int):
    """-> ("scan", chunk) | ("pallas", (block_n, block_v)). `chunk`
    doubles as the preferred pallas vocab block."""
    plan = _plan(n, v, block_n=256, block_v=max(chunk, 128))
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and plan is not None) else "scan"
    if impl == "scan":
        return "scan", chunk
    if impl == "pallas":
        if plan is None:
            raise ValueError(
                f"loss shape (rows={n}, vocab={v}) has no pallas block "
                "plan; use impl='scan'")
        return "pallas", plan
    raise ValueError(
        f"unknown fused-xent impl {impl!r} (expected 'auto' | 'pallas' "
        "| 'scan')")


def _lse_tgt_impl(x, embed, targets, chunk, impl):
    b, t, _ = x.shape
    kind, arg = _resolve_impl(impl, b * t, embed.shape[0], chunk)
    if kind == "scan":
        return _lse_tgt_scan(x, embed, targets, arg)
    return _lse_tgt_pallas(x, embed, targets, *arg,
                           interpret=jax.default_backend() != "tpu")


def _bwd_impl(x, embed, targets, lse, c_lse, c_tgt, chunk, impl):
    """f32 (dx, dembed); callers cast at the custom_vjp boundary (and
    the TP path psums in f32 first)."""
    b, t, _ = x.shape
    kind, arg = _resolve_impl(impl, b * t, embed.shape[0], chunk)
    if kind == "scan":
        return _bwd_scan(x, embed, targets, lse, c_lse, c_tgt, arg)
    return _bwd_pallas(x, embed, targets, lse, c_lse, c_tgt, *arg,
                       interpret=jax.default_backend() != "tpu")


def _int_zero(targets):
    return np.zeros(targets.shape, jax.dtypes.float0)


# ---------------------------------------------------------------------------
# single-shard op: custom_vjp over (partial lse, partial target logit)
# ---------------------------------------------------------------------------
# Exposing the PAIR (not the nll) keeps one vjp serving both the local
# loss (nll = lse - tgt, cotangents (g, -g)) and any composition that
# reduces partials across shards first.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _lse_and_target(x, embed, targets, chunk, impl):
    return _lse_tgt_impl(x, embed, targets, chunk, impl)


def _lse_and_target_fwd(x, embed, targets, chunk, impl):
    lse, tgt = _lse_tgt_impl(x, embed, targets, chunk, impl)
    return (lse, tgt), (x, embed, targets, lse)


def _lse_and_target_bwd(chunk, impl, res, cts):
    x, embed, targets, lse = res
    c_lse, c_tgt = cts
    dx, de = _bwd_impl(x, embed, targets, lse, c_lse, c_tgt, chunk, impl)
    return dx.astype(x.dtype), de.astype(embed.dtype), _int_zero(targets)


_lse_and_target.defvjp(_lse_and_target_fwd, _lse_and_target_bwd)


# ---------------------------------------------------------------------------
# vocab-sharded (tensor-parallel) composition
# ---------------------------------------------------------------------------

def _flat_axes(spec):
    out = []
    for entry in spec:
        if entry is None:
            continue
        out.extend((entry,) if isinstance(entry, str) else tuple(entry))
    return tuple(out)


def _tp_nll_and_lse(x, embed, targets, mesh, specs, vocab_axis, chunk,
                    impl):
    from ray_tpu.parallel.sharding import shard_map
    x_spec, e_spec, t_spec = specs

    def fwd(xs, es, ts):
        vloc = es.shape[0]
        base = jax.lax.axis_index(vocab_axis) * vloc
        lse_p, tgt_p = _lse_tgt_impl(xs, es, ts - base, chunk, impl)
        # psum of the partial log-sum-exp terms over the vocab axis,
        # max-shifted for stability; the partial target logit is nonzero
        # on exactly the shard owning the id, so a plain psum recovers it
        mg = jax.lax.pmax(lse_p, vocab_axis)
        lse = mg + jnp.log(
            jax.lax.psum(jnp.exp(lse_p - mg), vocab_axis))
        tgt = jax.lax.psum(tgt_p, vocab_axis)
        return lse - tgt, lse

    f = shard_map(fwd, mesh=mesh, in_specs=(x_spec, e_spec, t_spec),
                  out_specs=(t_spec, t_spec), check_vma=False)
    return f(x, embed, targets)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_xent_tp(x, embed, targets, mesh, specs, vocab_axis, chunk,
                   impl):
    nll, _ = _tp_nll_and_lse(x, embed, targets, mesh, specs, vocab_axis,
                             chunk, impl)
    return nll


def _fused_xent_tp_fwd(x, embed, targets, mesh, specs, vocab_axis, chunk,
                       impl):
    nll, lse = _tp_nll_and_lse(x, embed, targets, mesh, specs, vocab_axis,
                               chunk, impl)
    return nll, (x, embed, targets, lse)


def _fused_xent_tp_bwd(mesh, specs, vocab_axis, chunk, impl, res, g):
    from ray_tpu.parallel.sharding import shard_map
    x, embed, targets, lse = res
    x_spec, e_spec, t_spec = specs
    # dembed sums over every axis that shards tokens (its batch
    # reduction); dx sums the per-vocab-shard partials
    batch_axes = _flat_axes(t_spec)

    def bwd(xs, es, ts, lse_s, gs):
        vloc = es.shape[0]
        base = jax.lax.axis_index(vocab_axis) * vloc
        dx_p, de = _bwd_impl(xs, es, ts - base, lse_s, gs, -gs, chunk,
                             impl)
        dx = jax.lax.psum(dx_p, vocab_axis)
        if batch_axes:
            de = jax.lax.psum(de, batch_axes)
        return dx.astype(xs.dtype), de.astype(es.dtype)

    f = shard_map(
        bwd, mesh=mesh,
        in_specs=(x_spec, e_spec, t_spec, t_spec, t_spec),
        out_specs=(x_spec, e_spec), check_vma=False)
    dx, de = f(x, embed, targets, lse, g)
    return dx, de, _int_zero(targets)


_fused_xent_tp.defvjp(_fused_xent_tp_fwd, _fused_xent_tp_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def fused_softmax_xent(x, embed, targets, *, vocab_chunk: int = 512,
                       impl: str = "auto", mesh=None,
                       rules: dict | None = None):
    """Per-token nll [B, T] from pre-unembed activations, without ever
    materializing [B, T, V] logits (forward or backward).

    Same contract as ``spmd.softmax_xent(logits, targets)`` with the
    unembed matmul folded in: ``x [B, T, d_model]`` are the final-norm
    activations, ``embed [V, d_model]`` the tied embedding, and the
    implied logits are ``x @ embed.T`` accumulated in f32.

    With a `mesh` whose vocab rule axis (default ``tensor``) is >1-way,
    the embedding stays vocab-sharded: each shard reduces its local rows
    and one psum of the partial log-sum-exp / target-logit terms over
    that axis combines them (see `parallel.sharding.fused_xent_specs`).
    """
    if x.ndim != 3 or embed.ndim != 2:
        raise ValueError(
            f"fused_softmax_xent wants x [B, T, D] and embed [V, D]; got "
            f"{x.shape} and {embed.shape}")
    if mesh is not None:
        from ray_tpu.parallel.sharding import fused_xent_specs
        specs = fused_xent_specs(mesh, rules)
        vocab_axis = specs[1][0]
        if (isinstance(vocab_axis, str)
                and mesh.shape.get(vocab_axis, 1) > 1
                and embed.shape[0] % mesh.shape[vocab_axis] == 0):
            return _fused_xent_tp(x, embed, targets, mesh, specs,
                                  vocab_axis, vocab_chunk, impl)
    lse, tgt = _lse_and_target(x, embed, targets, vocab_chunk, impl)
    return lse - tgt
