"""Single-token decode attention over a resident KV cache — Pallas TPU
kernel plus a pure-JAX fallback with identical math.

The autoregressive hot path: one new query per sequence attends over that
sequence's cached keys/values. There is no O(T^2) score matrix here — per
(batch, head) the work is a [1, D] x [D, S] matvec — so the op is purely
HBM-bandwidth-bound (arithmetic intensity ~1 flop/byte). What the kernel
buys over the XLA fallback is the same thing flash_attention buys the
training path: the masked scores, softmax statistics and weighted sum all
live in VMEM while K/V blocks stream through, so the [B, H, S] score
tensor is never written to HBM and the per-position mask costs no extra
pass.

Structure mirrors `ops/flash_attention.py`: grid (B*H, S/block_kv) with
the kv dimension innermost/sequential, per-row running (m, l, acc)
softmax statistics in VMEM scratch, finalize on the last kv block. Two
decode-specific twists:

- **position masking**: each sequence attends to cache positions
  ``<= pos[b]`` (its current token's position — the caller writes the new
  K/V at ``pos`` *before* attending). ``pos`` rides in as a per-row
  [BH, 128] i32 tile (the fused_xent `_rows128` idiom).
- **data-dependent block skip**: kv blocks strictly past ``pos`` are
  predicated away with ``pl.when(k_start <= pos)`` — a *runtime* branch,
  unlike flash's static causal predicate — so short sequences in a long
  preallocated cache don't pay for the empty tail.

Layout: the public cache layout is ``[B, S, H, D]`` (matching
`models.gpt.init_kv_cache`'s ``[L, B, S, H, D]``); the kernel wants
(S, D) as the trailing tile per (b, h), so the wrapper transposes K/V to
``[B*H, S, D]`` on entry. The fallback consumes ``[B, S, H, D]``
directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.flash_attention import (
    _CompilerParams,
    _head_pad_target,
    _pad_heads,
    _pick_block,
)

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# pure-JAX fallback (the everywhere-correct path; CPU/CI default)
# ---------------------------------------------------------------------------

def reference_decode_attention(q, k, v, pos):
    """q [B, H, D]; k, v [B, S, H, D]; pos [B] i32. Attends to cache
    positions <= pos[b] and returns [B, H, D] in q.dtype. Accumulation is
    f32 regardless of input dtype (same contract as the kernel)."""
    b, s, h, d = k.shape
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    live = jnp.arange(s, dtype=jnp.int32)[None, None, :] <= \
        pos.astype(jnp.int32)[:, None, None]
    scores = jnp.where(live, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------

def _decode_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale: float,
                   block_kv: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[0, 0]
    k_start = ki * block_kv

    # Runtime predicate: blocks wholly past this row's position contribute
    # nothing — skip them (pos is data, so this is a dynamic branch, not
    # flash's static causal one).
    @pl.when(k_start <= pos)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [1, D]
        k = k_ref[0].astype(jnp.float32)            # [bkv, D]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, bkv]
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col <= pos, s, NEG_INF)
        m_prev = m_scr[:1, :1]                      # [1, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # [1, bkv]
        l_scr[:1, :1] = l_scr[:1, :1] * corr + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[:1, :1] = m_new
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, D]
        acc_scr[:1] = acc_scr[:1] * corr + pv

    # Finalize unconditionally at the last block: the last kv block may
    # itself be dead (pos early in the cache), but the output write must
    # still happen (flash's _finalize structure).
    @pl.when(ki == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:1] / l_scr[:1, :1]).astype(o_ref.dtype)


def _decode_bhsd(q, k, v, pos, *, sm_scale: float, block_kv: int,
                 interpret: bool):
    """q [BH, 1, D]; k, v [BH, S, D]; pos [BH, 128] i32 -> [BH, 1, D]."""
    bh, s, d = k.shape
    grid = (bh, s // block_kv)
    return pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale,
                          block_kv=block_kv),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, 128), lambda b, j: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),    # m (cell [0, 0] used)
            pltpu.VMEM((8, 128), jnp.float32),    # l
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 used)
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, pos)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def decode_attention(q, k, v, pos, *, impl: str = "auto",
                     block_kv: int = 512):
    """Decode-step attention: ``q [B, H, D]`` against a KV cache
    ``k, v [B, S, H, D]``, attending to positions ``<= pos[b]``
    (``pos [B]`` i32, the position of the token q was computed from).
    Returns ``[B, H, D]`` in q.dtype.

    impl: "auto" (pallas on TPU-friendly shapes, else jax) | "pallas" |
    "jax". The two paths share the same masking/accumulation math and
    agree to f32 tolerance."""
    if q.ndim != 3 or k.ndim != 4:
        raise ValueError(
            f"decode_attention wants q [B, H, D] and k/v [B, S, H, D]; "
            f"got {q.shape} and {k.shape}")
    b, s, h, d = k.shape
    bkv = _pick_block(s, block_kv)
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and bkv is not None) else "jax"
    if impl == "jax":
        return reference_decode_attention(q, k, v, pos)
    if impl != "pallas":
        raise ValueError(
            f"unknown decode_attention impl {impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    if bkv is None:
        raise ValueError(
            f"cache length {s} has no pallas block plan; use impl='jax'")
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    # [B, S, H, D] -> [B*H, S, D]: (S, D) become the trailing tile per
    # row. On TPU this is one cache-sized transpose per call — the price
    # of keeping the public cache layout sequence-major; a head-major
    # resident cache is the follow-up that removes it.
    kt = _pad_heads(k, d_pad).transpose(0, 2, 1, 3).reshape(b * h, s, d_pad)
    vt = _pad_heads(v, d_pad).transpose(0, 2, 1, 3).reshape(b * h, s, d_pad)
    qt = _pad_heads(q, d_pad).reshape(b * h, 1, d_pad)
    pos_rows = jnp.broadcast_to(
        pos.astype(jnp.int32).reshape(b, 1, 1), (b, h, 128)
    ).reshape(b * h, 128)
    out = _decode_bhsd(qt, kt, vt, pos_rows, sm_scale=d ** -0.5,
                       block_kv=bkv, interpret=interpret)
    return out.reshape(b, h, d_pad)[..., :d]
